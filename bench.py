"""Benchmark harness: tokens/sec/chip on the flagship model's train step.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The measured quantity is end-to-end optimizer-step throughput (forward +
backward + clip + AdamW + cosine schedule, all inside one jitted XLA
program) for the 2-term DiffTransformer at the reference recipe scale
(train.py:60-69: 8L/768d/4-head/block-512, micro-batch 32, vocab 12000),
bf16 compute / fp32 params, on whatever single device JAX provides (the
driver runs this on one real TPU chip).

``vs_baseline`` is the ratio against the reference implementation's
measured tokens/sec. The reference publishes no numbers (BASELINE.md), so
the baseline was measured by importing the reference's own DiffTransformer
from /root/reference and timing identical synthetic-data train steps on
this image's torch device (CPU-only torch; see tools/measure_reference.py
and BASELINE.md for the number's provenance and hardware caveat).

Env overrides: BENCH_STEPS, BENCH_WARMUP, BENCH_MICRO_BATCH, BENCH_MODEL,
BENCH_ATTN ("xla" | "pallas"), BENCH_FFN ("xla" | "pallas"),
BENCH_REMAT/BENCH_REMAT_POLICY, BENCH_LOSS_CHUNK.

BENCH_OUT=path appends the JSON line to a history file (one line per
run) — the trajectory ``tools/perf_gate.py`` gates and
``tools/bench_trend.py`` renders.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp


# Baseline denominator. The only number measurable in this environment is the
# reference's torch implementation on host CPU (torch here has no CUDA):
# 125.6 tokens/sec (tools/measure_reference.py, micro-batch 8, recipe shapes,
# 94.4M params). Dividing a TPU number by a CPU number would be meaningless,
# so vs_baseline instead uses a deliberately GENEROUS estimate of the
# reference on a modern single GPU (A100 fp16 AMP) — 2e5 tokens/sec — i.e.
# we assume the reference's eager per-head-Python-loop implementation
# (diff_transformer.py:89) still reaches 200k tok/s. Both numbers and the
# reasoning are recorded in BASELINE.md. The north-star target (BASELINE.json)
# is vs_baseline >= 4.
REFERENCE_TOKENS_PER_SEC = 2.0e5  # estimated reference-on-A100; see BASELINE.md
REFERENCE_TOKENS_PER_SEC_MEASURED_CPU = 125.6  # measured, this host


def main() -> None:
    from differential_transformer_replication_tpu.config import (
        ModelConfig,
        TrainConfig,
    )
    from differential_transformer_replication_tpu.train import (
        create_train_state,
        make_multi_train_step,
    )

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    # optimizer steps per jitted call (train/step.py:make_multi_train_step,
    # a lax.scan). Default 1 — exactly the launch pattern the trainer
    # (train/trainer.py) produces. K>1 amortizes per-launch PJRT argument
    # marshaling of the ~470-leaf state; measured WITHIN RUN-TO-RUN NOISE
    # on this platform (<=0.5% at K=10 vs K=1 — serial-launch marshaling
    # overlaps device compute in the pipelined loop), kept as an
    # experimentation knob only.
    spc = max(1, int(os.environ.get("BENCH_STEPS_PER_CALL", "1")))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    micro_batch = int(os.environ.get("BENCH_MICRO_BATCH", "32"))
    model_kind = os.environ.get("BENCH_MODEL", "diff")
    # pallas (the fused flash kernel) measured fastest at recipe scale
    # (186.0k vs XLA's ~175k tok/s with bf16 MXU operands + the custom
    # cross-entropy backward) and dominates at every longer context;
    # BENCH_ATTN=xla to compare.
    attn = os.environ.get("BENCH_ATTN", "pallas")
    # the fused FFN/norm path (ops/fused_ffn.py + fused_norm_residual.py:
    # block-boundary add+LN and the SwiGLU chain as Pallas kernels) is
    # the round-6 default; BENCH_FFN=xla reproduces the round-5 path.
    ffn = os.environ.get("BENCH_FFN", "pallas")
    # remat policy knob (only meaningful with BENCH_REMAT=1; sweep with
    # tools/ffn_sweep.py --remat-policies)
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "none")
    loss_chunk = int(os.environ.get("BENCH_LOSS_CHUNK", "0")) or None

    model = ModelConfig(
        model=model_kind,
        vocab_size=12000,
        n_embd=768,
        n_head=4,
        n_layer=8,
        block_size=512,
        dropout=0.0,
        compute_dtype="bfloat16",
        attention_impl=attn,
        ffn_impl=ffn,
        remat=remat,
        remat_policy=remat_policy,
        loss_chunk=loss_chunk,
    )
    cfg = TrainConfig(model=model, micro_batch_size=micro_batch, grad_acc_steps=1)

    key = jax.random.PRNGKey(0)
    state = create_train_state(key, cfg)
    step = make_multi_train_step(cfg, spc)

    T = model.block_size
    x = jax.random.randint(
        jax.random.PRNGKey(1), (spc, 1, micro_batch, T), 0, model.vocab_size
    )
    batch = {"x": x, "y": jnp.roll(x, -1, axis=-1)}

    # NOTE: sync via scalar readback, NOT block_until_ready — on the axon
    # TPU platform block_until_ready returns before the computation actually
    # finishes (measured: it reports physically impossible >1 PFLOP/s).
    # Successive steps are serialized by the state->state data dependence,
    # and float() forces a device->host transfer that cannot complete early.
    for _ in range(max(warmup, 1)):  # >=1 so `metrics` exists for the sync
        state, metrics = step(state, batch)
    _ = float(metrics["loss"][-1])

    # Best of BENCH_WINDOWS measurement windows: the shared axon TPU
    # service shows +-30% contention noise on short runs (measured via
    # tools/flash_sweep.py repeats); the fastest window is the least-
    # contended estimate of the chip's actual throughput.
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))
    calls = max(1, steps // spc)
    steps = calls * spc  # what actually runs (and what the stderr reports)
    window_secs = []
    # Zero-recompile sentinel (analysis/sanitizers.py): warmup compiled
    # everything this loop runs, so ANY compilation inside the measured
    # windows means the bench is silently timing retraces — fail loudly
    # (RecompileBudgetError) instead of reporting degraded tok/s.
    # BENCH_ALLOW_RECOMPILES=N loosens the pin for experiments (-1
    # disables it, like serve_bench's --allow-recompiles); the sentinel
    # adds no device ops, so the loss trajectory is unchanged.
    from differential_transformer_replication_tpu.analysis.sanitizers import (
        RecompileSentinel,
    )

    allow = int(os.environ.get("BENCH_ALLOW_RECOMPILES", "0"))
    budget = None if allow < 0 else allow
    with RecompileSentinel(budget=budget, name="bench-measured-window"):
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(calls):
                state, metrics = step(state, batch)
            _ = float(metrics["loss"][-1])
            window_secs.append(time.perf_counter() - t0)
    dt = min(window_secs)
    dt_median = statistics.median(window_secs)

    tok_per_window = calls * spc * micro_batch * T
    tps = tok_per_window / dt
    tps_median = tok_per_window / dt_median

    # MFU accounting. 6*N*D is the standard train-FLOPs estimate over
    # non-embedding params; the attention-inclusive number adds the
    # O(T^2) attention matmul FLOPs under the same 1-fwd + 2x-bwd
    # convention: per token per layer, each of the S softmax streams does
    # a QK and a PV contraction over ~(T+1)/2 visible keys.
    from differential_transformer_replication_tpu.models import param_count
    from differential_transformer_replication_tpu.obs.xprof import (
        embedding_param_count,
    )

    rm = cfg.resolved_model()
    n_params = param_count(state["params"])
    # one shared definition of "non-embedding params" (obs/xprof.py) so
    # this mfu_6nd and the continuous device_mfu gauge subtract the
    # same N
    n_embed = embedding_param_count(
        model_kind, model.vocab_size, model.n_embd, model.block_size
    )
    flops_per_tok = 6 * (n_params - n_embed)
    n_streams = {"control": 1, "diff": 2, "ndiff": rm.n_terms}[model_kind]
    d_qk = rm.head_size
    d_v = d_qk if model_kind == "control" else 2 * d_qk
    attn_fwd = (
        rm.n_layer * rm.n_head * n_streams * 2 * (d_qk + d_v) * (T + 1) / 2
    )
    flops_per_tok_attn = flops_per_tok + 3 * attn_fwd
    peak = 197e12  # TPU v5e bf16 peak FLOP/s

    line = json.dumps(
        {
            "metric": "train_tokens_per_sec_per_chip",
            "value": round(tps, 1),
            "unit": "tokens/sec",
            # vs the deliberately GENEROUS estimate of the reference on
            # a modern GPU (see header) — the conservative ratio
            "vs_baseline": round(tps / REFERENCE_TOKENS_PER_SEC, 2),
            # vs the only MEASURED reference number (torch on this
            # host's CPU; tools/measure_reference.py)
            "vs_reference_measured_cpu": round(
                tps / REFERENCE_TOKENS_PER_SEC_MEASURED_CPU, 1
            ),
            "mfu_6nd": round(tps * flops_per_tok / peak, 3),
            "mfu_attn_incl": round(tps * flops_per_tok_attn / peak, 3),
            # dispersion across the timing windows, machine-readable:
            # `value` is min-of-N (least-contended estimate on the
            # shared chip); median + raw windows let readers compare
            # like-for-like estimators across rounds (ADVICE r2)
            "tokens_per_sec_median": round(tps_median, 1),
            "window_secs": [round(w, 4) for w in window_secs],
        }
    )
    print(line)
    # append to the trajectory file perf_gate/bench_trend consume
    out_path = os.environ.get("BENCH_OUT")
    if out_path:
        with open(out_path, "a") as f:
            f.write(line + "\n")
    # diagnostics on stderr so stdout stays one JSON line
    print(
        f"[bench] model={model_kind} attn={attn} ffn={ffn} "
        f"device={jax.devices()[0].device_kind} "
        f"micro_batch={micro_batch} block={T} steps={steps} "
        f"tok/s best..median={tps:.0f}..{tps_median:.0f} "
        f"sec/step={dt / (calls * spc):.4f} steps_per_call={spc} "
        f"loss={float(metrics['loss'][-1]):.4f} "
        f"mfu~{tps * flops_per_tok / peak:.1%} "
        f"(attn-incl {tps * flops_per_tok_attn / peak:.1%})",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
