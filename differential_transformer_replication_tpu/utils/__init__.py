"""Host-side utilities.

:mod:`utils.faults` is stdlib-only and imported eagerly — the fault
harness must be armable from supervisor/router processes that never
touch the device runtime. The profiling helpers pull in jax, so they
resolve lazily (PEP 562): ``from ...utils import ProfilerWindow`` works
as before but pays the jax import at first access, keeping
``from ...utils import faults`` jax-free.
"""

from typing import TYPE_CHECKING

from differential_transformer_replication_tpu.utils import faults

_LAZY = {"ProfilerWindow", "Throughput", "trace"}

__all__ = ["ProfilerWindow", "Throughput", "trace", "faults"]

if TYPE_CHECKING:
    from differential_transformer_replication_tpu.utils.profiling import (
        ProfilerWindow,
        Throughput,
        trace,
    )


def __getattr__(name: str):
    if name not in _LAZY:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    from differential_transformer_replication_tpu.utils import profiling

    value = getattr(profiling, name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
