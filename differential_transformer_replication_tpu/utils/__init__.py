from differential_transformer_replication_tpu.utils import faults
from differential_transformer_replication_tpu.utils.profiling import (
    ProfilerWindow,
    Throughput,
    trace,
)

__all__ = ["ProfilerWindow", "Throughput", "trace", "faults"]
