"""Export this framework's checkpoints to the reference (PyTorch) formats.

The inverse of utils/torch_import.py: a params pytree trained here maps
back onto the reference modules' ``state_dict`` layout, so weights flow
BOTH ways — a reference user can bring their checkpoint over, train on
TPU, and hand the result back to the original torch code. Both on-disk
shapes the reference knows are produced:

  - the ``save_pretrained`` blob ``{'model_args', 'model_state'}``
    (Ndiff_transformer.py:251-265) — loadable by the reference's own
    ``AlternatingDiffTransformer.from_pretrained``
    (Ndiff_transformer.py:243-249) for the ndiff family, and by
    ``load_state_dict`` for the other two,
  - the ``best_model.pt`` training-blob key layout
    (``{'model_state_dict': ...}``, train.py:309-316).

Layout translation (exact inverse of the importer):
  - our ``(in, out)`` weights transpose back to torch Linear's
    ``(out, in)``,
  - merged-head tensors (``wq: (streams, E, H, d)``) split into the
    per-head ``ModuleList`` entries (``heads.{h}.query1.weight`` etc.,
    diff_transformer.py:26-30),
  - GroupLayerNorm affine params unflatten to the reference's
    ``(1, 1, C)`` registration (diff_transformer.py:12-13),
  - derived buffers the reference registers are SYNTHESIZED so
    ``load_state_dict(strict=True)`` passes: ``tril``
    (control.py:31), complex RoPE ``freqs_cis`` (control.py:4-9,
    re-derived with torch.polar), per-head ``lambda_init`` at its
    dynamic per-layer value ``0.8 - 0.6*exp(-0.3*(layer-1))`` — the
    value any used reference model holds, since its forward writes the
    buffer in place (diff_transformer.py:41-48) — and the multi-head
    module's CONSTANT 0.8 (never updated, diff_transformer.py:86).

torch is imported lazily, like the importer.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from differential_transformer_replication_tpu.config import ModelConfig


def _t(a):
    import torch

    if not hasattr(a, "dtype"):
        # Python scalar constants built here (lambda_init values, the 0.8
        # buffer) — not param leaves; np.asarray would type them float64
        return torch.tensor(np.float32(a))
    a = np.asarray(a)
    # Exporting is a parity surface: the reference's state_dicts are fp32,
    # and so are this framework's params (config.py:param_dtype). A non-fp32
    # leaf here means the params came from somewhere unexpected (e.g. a
    # future bf16-saved checkpoint) — fail loud rather than silently upcast.
    if a.dtype != np.float32:
        raise TypeError(
            f"expected float32 params (param_dtype), got {a.dtype}; cast "
            "explicitly before export if the rewrite is intended"
        )
    return torch.tensor(a)


def _lin(out: dict, prefix: str, p: dict) -> None:
    """{'w': (in, out)[, 'b']} -> torch Linear entries at ``prefix``."""
    out[prefix + ".weight"] = _t(p["w"]).T.contiguous()
    if "b" in p:
        out[prefix + ".bias"] = _t(p["b"])


def _norm(out: dict, prefix: str, p: dict, shape=None) -> None:
    w, b = _t(p["w"]), _t(p["b"])
    if shape is not None:
        w, b = w.reshape(shape), b.reshape(shape)
    out[prefix + ".weight"] = w
    out[prefix + ".bias"] = b


def _ffn(out: dict, prefix: str, p: dict) -> None:
    """Our ffn dict -> the reference FFN Sequential (SwiGLU at index 0,
    down-proj at index 1, control.py:100-104)."""
    _lin(out, f"{prefix}.0.linear_gate", p["gate"])
    _lin(out, f"{prefix}.0.linear_xform", p["xform"])
    _lin(out, f"{prefix}.1", p["out"])


def _tril(block_size: int):
    import torch

    return torch.tril(torch.ones(block_size, block_size))


def _freqs_cis(dim: int, end: int, theta: float = 10000.0):
    """The reference's complex RoPE table (control.py:4-9 semantics:
    polar(1, outer(t, 1/theta^(2i/dim)))), rebuilt with torch ops."""
    import torch

    freqs = 1.0 / (
        theta ** (torch.arange(0, dim, 2)[: dim // 2].float() / dim)
    )
    t = torch.arange(end).float()
    return torch.polar(torch.ones(end, dim // 2), torch.outer(t, freqs))


def _dynamic_lambda_init(layer_idx_1based: int):
    """The per-layer value the reference's in-place buffer write leaves
    behind after a forward (diff_transformer.py:41-48, 1-based layers)."""
    return _t(0.8 - 0.6 * math.exp(-0.3 * (layer_idx_1based - 1)))


def export_reference_state_dict(params: dict, cfg: ModelConfig) -> dict:
    """This framework's params pytree -> the reference model's full
    ``state_dict`` (params + synthesized buffers), float32, strict-load
    compatible with the matching reference class."""
    H, T = cfg.n_head, cfg.block_size
    # derived buffers are identical across layers/heads: build each ONCE
    # and share the tensor (torch.save dedups shared storage)
    tril = _tril(T)
    freqs_cache: dict = {}

    def freqs(dim: int):
        if dim not in freqs_cache:
            freqs_cache[dim] = _freqs_cis(dim, T)
        return freqs_cache[dim]

    sd: dict = {}
    sd["token_embedding_table.weight"] = _t(params["tok_emb"])
    if cfg.model == "diff":
        sd["position_embedding_table.weight"] = _t(params["pos_emb"])
    _norm(sd, "ln_f", params["ln_f"])
    _lin(sd, "lm_head", params["lm_head"])

    for i, blk in enumerate(params["blocks"]):
        b = f"blocks.{i}"
        _norm(sd, f"{b}.ln1", blk["ln1"])
        _norm(sd, f"{b}.ln2", blk["ln2"])
        _ffn(sd, f"{b}.ffwd", blk["ffn"])
        attn = blk["attn"]
        if cfg.model == "control":
            a = f"{b}.attn"
            wq, wk, wv = (np.asarray(attn[k]) for k in ("wq", "wk", "wv"))
            d = wq.shape[-1]
            for h in range(H):
                hp = f"{a}.heads.{h}"
                sd[f"{hp}.query.weight"] = _t(wq[:, h, :]).T.contiguous()
                sd[f"{hp}.key.weight"] = _t(wk[:, h, :]).T.contiguous()
                sd[f"{hp}.value.weight"] = _t(wv[:, h, :]).T.contiguous()
                sd[f"{hp}.tril"] = tril
                sd[f"{hp}.freqs_cis"] = freqs(d)
            _lin(sd, f"{a}.proj", attn["out"])
        elif cfg.model == "diff":
            a = f"{b}.diff_attn"
            wq, wk, wv = (np.asarray(attn[k]) for k in ("wq", "wk", "wv"))
            lq, lk = np.asarray(attn["lambda_q"]), np.asarray(attn["lambda_k"])
            li = _dynamic_lambda_init(i + 1)
            for h in range(H):
                hp = f"{a}.heads.{h}"
                for s in (1, 2):
                    sd[f"{hp}.query{s}.weight"] = _t(
                        wq[s - 1, :, h, :]
                    ).T.contiguous()
                    sd[f"{hp}.key{s}.weight"] = _t(
                        wk[s - 1, :, h, :]
                    ).T.contiguous()
                    sd[f"{hp}.lambda_q{s}"] = _t(lq[s - 1, h])
                    sd[f"{hp}.lambda_k{s}"] = _t(lk[s - 1, h])
                sd[f"{hp}.value.weight"] = _t(wv[:, h, :]).T.contiguous()
                sd[f"{hp}.tril"] = tril
                sd[f"{hp}.lambda_init"] = li
            _norm(sd, f"{a}.group_norm", attn["gn"], shape=(1, 1, -1))
            sd[f"{a}.lambda_init"] = _t(0.8)  # constant, never updated
            _lin(sd, f"{a}.proj", attn["out"])
        else:  # ndiff
            a = f"{b}.diff_attn"
            wq, wk, wv = (np.asarray(attn[k]) for k in ("wq", "wk", "wv"))
            lq, lk = np.asarray(attn["lambda_q"]), np.asarray(attn["lambda_k"])
            n, d = wq.shape[0], wq.shape[-1]
            li = _dynamic_lambda_init(i + 1)
            for h in range(H):
                hp = f"{a}.heads.{h}"
                for t_i in range(n):
                    sd[f"{hp}.queries.{t_i}.weight"] = _t(
                        wq[t_i, :, h, :]
                    ).T.contiguous()
                    sd[f"{hp}.keys.{t_i}.weight"] = _t(
                        wk[t_i, :, h, :]
                    ).T.contiguous()
                    sd[f"{hp}.lambda_qs.{t_i}"] = _t(lq[t_i, h])
                    sd[f"{hp}.lambda_ks.{t_i}"] = _t(lk[t_i, h])
                sd[f"{hp}.value.weight"] = _t(wv[:, h, :]).T.contiguous()
                sd[f"{hp}.tril"] = tril
                sd[f"{hp}.freqs_cis"] = freqs(d)
                sd[f"{hp}.lambda_init"] = li
            _norm(sd, f"{a}.group_norm", attn["gn"], shape=(1, 1, -1))
            sd[f"{a}.lambda_init"] = _t(0.8)
            _lin(sd, f"{a}.proj", attn["out"])
    return sd


def save_reference_checkpoint(
    path: str,
    params: dict,
    cfg: ModelConfig,
    fmt: str = "pretrained",
    extra: Optional[dict] = None,
) -> None:
    """Write a torch checkpoint the reference code can consume.

    ``fmt='pretrained'``: the ``save_pretrained`` blob
    ``{'model_args', 'model_state'}`` with the reference's introspected
    arg set (Ndiff_transformer.py:253-260; n_terms included only for
    ndiff, mirroring the constructor signatures). For ndiff this loads
    directly via ``AlternatingDiffTransformer.from_pretrained``.

    ``fmt='train'``: the ``best_model.pt`` key layout
    (``{'model_state_dict': ...}``, train.py:309-316); ``extra`` entries
    (e.g. iter_num, best_val_loss) merge into the blob.
    """
    import torch

    sd = export_reference_state_dict(params, cfg)
    if fmt == "pretrained":
        model_args = {
            "vocab_size": cfg.vocab_size,
            "n_embd": cfg.n_embd,
            "n_head": cfg.n_head,
            "n_layer": cfg.n_layer,
            "block_size": cfg.block_size,
            "dropout": cfg.dropout,
        }
        if cfg.model == "ndiff":
            model_args["n_terms"] = cfg.n_terms
        blob = {"model_args": model_args, "model_state": sd}
    elif fmt == "train":
        blob = {"model_state_dict": sd, **(extra or {})}
    else:
        raise ValueError(f"unknown export format {fmt!r}")
    torch.save(blob, path)
