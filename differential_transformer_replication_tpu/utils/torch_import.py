"""Import reference (PyTorch) checkpoints into this framework.

The reference saves weights in two formats: the best-model training blob
(``torch.save({'model_state_dict': ...}, 'best_model.pt')``,
train.py:309-316) and the N-diff ``save_pretrained`` directory
(``{'model_args', 'model_state'}``, Ndiff_transformer.py:251-265). This
module maps either state_dict onto this framework's param pytrees for
all three families, so a user of the reference can bring trained weights
straight over (and so the test suite can prove cross-implementation
numerical parity against the reference's own forward pass,
tests/test_torch_import.py).

Layout translation (names from the reference modules):
  - torch ``nn.Linear`` stores ``(out, in)``; we store ``(in, out)`` —
    every weight is transposed,
  - per-head ``nn.ModuleList`` projections (``heads.{h}.query1`` etc.,
    diff_transformer.py:26-30) are stacked into our merged-head tensors
    (``wq: (streams, E, H, d)``),
  - ``GroupLayerNorm``'s ``(1, 1, C)`` affine params flatten to ``(C,)``,
  - buffers (``tril``, ``lambda_init``, RoPE ``freqs``) are derived
    quantities here and are skipped.

torch is imported lazily: the framework never needs it unless a torch
checkpoint is actually being imported.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from differential_transformer_replication_tpu.config import ModelConfig


def _np(t) -> np.ndarray:
    return np.asarray(t.detach().cpu().numpy(), dtype=np.float32)


def _lin(sd: dict, prefix: str) -> dict:
    """torch Linear -> {'w': (in, out)[, 'b': (out,)]}."""
    p = {"w": _np(sd[prefix + ".weight"]).T}
    if prefix + ".bias" in sd:
        p["b"] = _np(sd[prefix + ".bias"])
    return p


def _norm(sd: dict, prefix: str) -> dict:
    return {
        "w": _np(sd[prefix + ".weight"]).reshape(-1),
        "b": _np(sd[prefix + ".bias"]).reshape(-1),
    }


def _ffn(sd: dict, prefix: str) -> dict:
    """The reference FFN Sequential: SwiGLU (linear_gate/linear_xform) at
    index 0, down-proj Linear at index 1 (control.py:100-104)."""
    return {
        "gate": _lin(sd, f"{prefix}.0.linear_gate"),
        "xform": _lin(sd, f"{prefix}.0.linear_xform"),
        "out": _lin(sd, f"{prefix}.1"),
    }


def infer_model_config(sd: dict, dropout: float = 0.0) -> ModelConfig:
    """Reconstruct a ModelConfig from a reference state_dict's shapes.

    The family is identified structurally: a position table means the
    2-term DiffTransformer (the only variant with one,
    diff_transformer.py:133-134); ``attn.heads`` means the vanilla
    control; ``queries.0`` under diff_attn means the N-term model.

    Limits of inference: a state_dict carries no training-time
    hyperparameters, so ``dropout`` is whatever the caller passes
    (default 0.0 — the reference's training value, train.py:64; inference
    is unaffected either way), and non-ndiff families take the
    ModelConfig default ``n_terms`` rather than a fabricated value."""
    vocab_size, n_embd = _np(sd["token_embedding_table.weight"]).shape
    n_layer = 1 + max(
        int(k.split(".")[1]) for k in sd if k.startswith("blocks.")
    )
    if "position_embedding_table.weight" in sd:
        model = "diff"
        attn = "diff_attn"
        block_size = _np(sd["position_embedding_table.weight"]).shape[0]
    elif any(".attn.heads." in k for k in sd):
        model = "control"
        attn = "attn"
        block_size = sd["blocks.0.attn.heads.0.tril"].shape[0]
    else:
        model = "ndiff"
        attn = "diff_attn"
        block_size = sd["blocks.0.diff_attn.heads.0.tril"].shape[0]
    # key shape: blocks.{i}.{attn}.heads.{h}.{...}; h is field 4
    n_head = 1 + max(
        int(k.split(".")[4])
        for k in sd
        if k.startswith(f"blocks.0.{attn}.heads.")
    )
    n_terms = 0
    if model == "ndiff":
        # blocks.0.diff_attn.heads.0.queries.{t}.weight; t is field 6
        n_terms = 1 + max(
            int(k.split(".")[6])
            for k in sd
            if k.startswith("blocks.0.diff_attn.heads.0.queries.")
        )
    kwargs = {}
    if model == "ndiff":
        kwargs["n_terms"] = max(n_terms, 1)
    # non-ndiff families keep the ModelConfig default — n_terms is inert
    # for them, and inventing a value would mis-round-trip the config
    return ModelConfig(
        model=model,
        vocab_size=int(vocab_size),
        n_embd=int(n_embd),
        n_head=int(n_head),
        n_layer=int(n_layer),
        block_size=int(block_size),
        dropout=dropout,
        **kwargs,
    )


def _stack_heads(sd, names, transpose=True):
    """[per-head torch arrays] -> (E, H, d) (or (H, d) for vectors)."""
    arrs = [_np(sd[n]) for n in names]
    if transpose:
        return np.stack([a.T for a in arrs], axis=1)  # (E, H, d)
    return np.stack(arrs, axis=0)  # (H, d)


def import_reference_state_dict(
    sd: dict, cfg: Optional[ModelConfig] = None
) -> Tuple[dict, ModelConfig]:
    """Reference torch ``state_dict`` -> (this framework's params pytree,
    inferred-or-given ModelConfig). Values are float32 numpy arrays (the
    param dtype; compute dtype is applied at forward time)."""
    if cfg is None:
        cfg = infer_model_config(sd)
    H, L = cfg.n_head, cfg.n_layer

    params: dict = {
        "tok_emb": _np(sd["token_embedding_table.weight"]),
        "ln_f": _norm(sd, "ln_f"),
        "lm_head": _lin(sd, "lm_head"),
    }
    if cfg.model == "diff":
        params["pos_emb"] = _np(sd["position_embedding_table.weight"])

    blocks = []
    for i in range(L):
        b = f"blocks.{i}"
        if cfg.model == "control":
            a = f"{b}.attn"
            attn = {
                "wq": _stack_heads(sd, [f"{a}.heads.{h}.query.weight" for h in range(H)]),
                "wk": _stack_heads(sd, [f"{a}.heads.{h}.key.weight" for h in range(H)]),
                "wv": _stack_heads(sd, [f"{a}.heads.{h}.value.weight" for h in range(H)]),
                "out": _lin(sd, f"{a}.proj"),
            }
        elif cfg.model == "diff":
            a = f"{b}.diff_attn"
            attn = {
                # streams stacked first: (2, E, H, d) from query1/query2
                "wq": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.query{s}.weight" for h in range(H)])
                    for s in (1, 2)
                ]),
                "wk": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.key{s}.weight" for h in range(H)])
                    for s in (1, 2)
                ]),
                "wv": _stack_heads(sd, [f"{a}.heads.{h}.value.weight" for h in range(H)]),
                "lambda_q": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.lambda_q{s}" for h in range(H)], transpose=False)
                    for s in (1, 2)
                ]),
                "lambda_k": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.lambda_k{s}" for h in range(H)], transpose=False)
                    for s in (1, 2)
                ]),
                "gn": _norm(sd, f"{a}.group_norm"),
                "out": _lin(sd, f"{a}.proj"),
            }
        else:  # ndiff
            a = f"{b}.diff_attn"
            n = cfg.n_terms
            attn = {
                "wq": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.queries.{t}.weight" for h in range(H)])
                    for t in range(n)
                ]),
                "wk": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.keys.{t}.weight" for h in range(H)])
                    for t in range(n)
                ]),
                "wv": _stack_heads(sd, [f"{a}.heads.{h}.value.weight" for h in range(H)]),
                "lambda_q": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.lambda_qs.{t}" for h in range(H)], transpose=False)
                    for t in range(n)
                ]),
                "lambda_k": np.stack([
                    _stack_heads(sd, [f"{a}.heads.{h}.lambda_ks.{t}" for h in range(H)], transpose=False)
                    for t in range(n)
                ]),
                "gn": _norm(sd, f"{a}.group_norm"),
                "out": _lin(sd, f"{a}.proj"),
            }
        blocks.append({
            "ln1": _norm(sd, f"{b}.ln1"),
            "attn": attn,
            "ln2": _norm(sd, f"{b}.ln2"),
            "ffn": _ffn(sd, f"{b}.ffwd"),
        })
    params["blocks"] = blocks
    return params, cfg


def load_reference_checkpoint(path: str) -> Tuple[dict, ModelConfig]:
    """Load either reference on-disk format:

    - ``best_model.pt`` training blob (train.py:309-316): reads
      ``model_state_dict``,
    - ``save_pretrained`` file (Ndiff_transformer.py:251-265): reads
      ``model_state`` (+ ``model_args`` for dropout/n_terms hints).
    """
    import torch

    blob = torch.load(path, map_location="cpu", weights_only=False)
    if "model_state_dict" in blob:
        sd = blob["model_state_dict"]
    elif "model_state" in blob:
        sd = blob["model_state"]
    else:
        raise ValueError(
            f"unrecognized checkpoint structure at {path!r}: keys "
            f"{sorted(blob)[:8]} (expected 'model_state_dict' or 'model_state')"
        )
    params, cfg = import_reference_state_dict(sd)
    # honor save_pretrained's model_args where they carry information the
    # state_dict cannot (dropout; Ndiff_transformer.py:253-260)
    args = blob.get("model_args")
    if isinstance(args, dict) and "dropout" in args:
        cfg = cfg.replace(dropout=float(args["dropout"]))
    return params, cfg
