"""JAX version-compatibility aliases.

The repo targets current JAX but must also run on the older runtimes
some environments pin (e.g. 0.4.x, where ``shard_map`` still lives in
``jax.experimental`` with the ``check_rep``/``auto`` kwarg spellings,
and Pallas-TPU compiler params are named ``TPUCompilerParams``). Import
the symbols from here instead of version-probing at every call site.
Call sites use the CURRENT spellings (``check_vma=``, ``axis_names=``);
the wrapper translates for old runtimes.
"""

from __future__ import annotations

import inspect

import jax
from jax.experimental.pallas import tpu as pltpu

try:  # jax >= 0.4.35: top-level export
    _raw_shard_map = jax.shard_map
except AttributeError:  # older: experimental namespace
    from jax.experimental.shard_map import shard_map as _raw_shard_map

if "check_vma" in inspect.signature(_raw_shard_map).parameters:
    shard_map = _raw_shard_map
else:

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None,
                  axis_names=None, **kw):
        """Old-API adapter: ``check_vma`` was ``check_rep``; manual
        ``axis_names`` were spelled as their complement ``auto``."""
        if check_vma is not None:
            kw["check_rep"] = check_vma
        if axis_names is not None:
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _raw_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:

    def axis_size(name):
        """Size of a named mesh axis inside shard_map. ``psum`` of the
        Python constant 1 is evaluated eagerly to a concrete int, so
        this is usable in host control flow exactly like the real
        ``jax.lax.axis_size``."""
        return jax.lax.psum(1, name)


# Renamed TPUCompilerParams -> CompilerParams when pallas TPU stabilized.
CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

__all__ = ["shard_map", "CompilerParams"]
