"""Fault-injection harness: deterministic failures for chaos tests.

None of the crash/resume machinery (SIGTERM graceful stop, rescue
checkpoints, the anomaly guard, the crash supervisor) is trustworthy
until a test actually kills a run mid-flight — this module is the
injection side of those tests (tests/test_faults.py). It is inert
unless explicitly armed; nothing here imports jax, so the supervisor
and checkpoint layer can use it without device initialization.

A fault PLAN is a comma-separated spec of ``kind@step`` (or
``kind@a-b`` for an inclusive step range, or bare ``kind`` for
call-point faults):

  ``raise@K``           raise :class:`FaultInjected` at the top of
                        training iteration K (a generic crash)
  ``sigterm@K``         SIGTERM self at iteration K (exercises the
                        graceful-stop path, trainer.py)
  ``sigkill@K``         SIGKILL self at iteration K — uncatchable, no
                        cleanup runs (the preemption/hard-crash case)
  ``nan@K`` / ``nan@A-B``
                        NaN-poison the loss of the batch(es) at those
                        iterations (the trainer threads a poison scale
                        into the jitted step; the gradient inherits the
                        NaN, so the whole update is bad)
  ``corrupt_params@K``  overwrite one param leaf with NaN before
                        iteration K — state corruption that batch
                        skipping CANNOT cure; only rollback recovers
  ``ckpt_write`` / ``ckpt_write@N``
                        fail the next (or the Nth upcoming) checkpoint
                        file write, AFTER the temp file is written but
                        BEFORE the atomic rename — the crash point
                        ``atomic_write`` exists to survive
  ``ckpt_fsync``        fail a checkpoint file write AFTER the rename
                        but BEFORE the parent-directory fsync — the
                        window where a power cut can roll the rename
                        back (train/ckpt_writer.py:atomic_write)
  ``ckpt_manifest``     fail a checkpoint save just before the
                        manifest write: leaves a complete but
                        UNcertified directory that latest-resolution
                        and resume must skip
  ``ckpt_gc``           fail retention GC between a checkpoint's
                        de-certification (manifest removed) and its
                        data deletion — the crash-safe-delete-ordering
                        window (train/ckpt_writer.py)
  ``ckpt_hang`` / ``ckpt_hang@N``
                        stall the Nth upcoming async checkpoint save
                        for ``DTX_CKPT_HANG_S`` seconds (default 2.0)
                        inside the writer THREAD — proves the train
                        loop keeps stepping while checkpoint I/O drags
                        and exercises submit() back-pressure
  ``train_hang@K``      stall the HOST train loop at iteration K for
                        ``DTX_TRAIN_HANG_S`` seconds (default 30.0) —
                        the wedge a dead peer or a stuck collective
                        produces; the step-deadline watchdog's trigger
                        (train/watchdog.py). One-shot.
  ``collective_skew@K`` stall iteration K for ``DTX_SKEW_S`` seconds
                        (default 0.5) — one host entering the step's
                        collectives LATE. Short enough that a sane
                        watchdog budget must tolerate it (skew is
                        normal; silence is not). One-shot.
  ``heartbeat_silence@P``
                        MUTE heartbeat publications from process index
                        P (parallel/heartbeat.py skips its publish) —
                        a host that is alive but unreachable; peers
                        must see its heartbeat age grow past
                        ``heartbeat_timeout_s`` and coordinate an
                        abort. NOT one-shot: the peer stays silent.

Serving fault points (``@N`` counts ENGINE iterations —
``ServingEngine.stats["iterations"]`` — not training steps; exercised
by tests/test_serving_resilience.py against the engine supervision in
serving/server.py):

  ``serve_raise@N``     raise :class:`FaultInjected` at the top of
                        engine iteration N (a mid-batch engine crash)
  ``serve_hang@N``      stall engine iteration N for
                        ``DTX_SERVE_HANG_S`` seconds (default 2.0) —
                        the step-time watchdog's trigger
  ``serve_corrupt@N``   NaN-poison one occupied slot's KV rows before
                        iteration N's decode; the engine's finite-logits
                        guard turns this into a typed EngineCrashError
                        that the supervised restart recovers from
  ``page_exhaust@N``    make the paged KV pool (serving/pages.py)
                        refuse its next admission plan with a typed
                        PagePoolExhaustedError at engine iteration N —
                        the request is shed through the 503 queue-shed
                        path instead of waiting or crashing
  ``prefix_corrupt@N``  NaN-poison one radix-CACHED prefix page before
                        iteration N's decode (preferring one shared
                        with an occupied slot): the finite-logits
                        guard fires, the supervised restart rebuilds
                        pool + radix tree, and the poisoned prefix is
                        evicted instead of ever serving garbage tokens
  ``spec_drafter_crash@N``
                        NaN-poison the speculative drafter's own KV
                        pool (serving/spec.py:ModelDrafter) before
                        engine iteration N's proposals: the drafter's
                        finite-logits reduction trips, it rebuilds
                        from params and proposes nothing, and the
                        engine falls back to the non-spec decode step
                        — never garbage tokens. One-shot.
  ``spec_reject_storm@N`` / ``spec_reject_storm@A-B``
                        force the fused verify step to REJECT every
                        drafted token at those engine iterations (a
                        pathological drafter): throughput must
                        degrade gracefully to ~non-spec — one emitted
                        token per slot per step, outputs still exact.
                        NOT one-shot: a range is a storm window.
  ``constrain_dead_end@N``
                        poison one constrained ACTIVE slot's FSM
                        cursor with the dead-end sentinel before
                        engine iteration N's decode: every token is
                        masked out, and the engine must retire the
                        request TYPED (finish_reason
                        "constraint_dead_end", partial output
                        delivered, slot + pages reclaimed) — never
                        hang, never emit a garbage token. One-shot.
                        Compiled FSMs prune dead states (Willard &
                        Louf), so only this fault reaches the
                        non-accepting zero-mask sweep.
  ``page_demote_fail@N``
                        fail the host-tier page demotions drained at
                        engine iteration N (serving/host_tier.py): the
                        evicted pages' device capture is skipped, the
                        prefix is simply LOST from the tier (counted
                        ``serving_host_tier_fallbacks_total``), and the
                        next request for it recomputes — degradation
                        back to pre-tier behavior, never a wedge.
                        One-shot.
  ``page_promote_hang@N``
                        stall the promotions applied at engine
                        iteration N for ``DTX_TIER_HANG_S`` seconds
                        (default 2.0), then FAIL them: the admission
                        truncates its cached length back to the
                        device-resident prefix and prefills the rest —
                        recompute fallback, typed and counted, never a
                        hang past the stall or garbage KV. One-shot.
  ``page_swap_corrupt@N``
                        flip one byte of a stashed page image before
                        the swap-in at engine iteration N: the CRC32
                        verify at injection must catch it, drop the
                        stash, and fall back to a full bit-exact
                        restart of the request (fold_in per-request
                        keys) — never garbage tokens. One-shot.
  ``quality_drift@N``   perturb the model's params before engine
                        iteration N (layer-1 λ for diff/ndiff; an
                        exact lm_head logit rescale for control, so
                        greedy outputs stay IDENTICAL) — logits stay
                        finite and latency flat, only the token-
                        quality distribution moves; the drift
                        fingerprint (obs/quality.py,
                        ``serving_quality_drift``) is the ONLY
                        detector that can catch it. Requires
                        ``--quality-telemetry``. One-shot; persists in
                        the params until restart.
  ``quality_nan@N``     NaN-poison the HOST-side quality telemetry of
                        engine iteration N (the decode step itself is
                        untouched): every signal that iteration must
                        degrade to "no signal" — skipped
                        observations, never a crash, never a drift
                        false-positive. Requires
                        ``--quality-telemetry``. One-shot.

Constraint fault points (call-point style — ``@N`` counts CALLS):

  ``constrain_compile_fail`` / ``constrain_compile_fail@N``
                        fail the Nth upcoming constraint FSM compile
                        (serving/constrain.py:compile_constraint)
                        with the typed ConstraintCompileError: the
                        submit path must reject the request (HTTP
                        400 "constraint_compile_failed") with the
                        engine untouched — no queue entry, no slot,
                        no cache reference.

Router fault points (call-point style like ``ckpt_*`` — ``@N`` counts
CALLS until the fault fires, default 1; exercised by
tests/test_router.py against serving/router.py):

  ``router_probe_fail`` / ``router_probe_fail@N``
                        fail the Nth upcoming health probe (the prober
                        treats it like an unreachable replica — drives
                        the ejection state machine deterministically)
  ``router_replica_hang`` / ``router_replica_hang@N``
                        stall the Nth upcoming forwarded request for
                        ``DTX_ROUTER_HANG_S`` seconds (default 2.0)
                        before it leaves the router — a hung replica
                        from the client's view; the hedging trigger
  ``router_pick_raise`` / ``router_pick_raise@N``
                        raise :class:`FaultInjected` inside the Nth
                        upcoming replica pick — an unexpected router
                        bug; must surface as a typed 500, never kill
                        the router process
  ``router_stale_metrics`` / ``router_stale_metrics@N``
                        SKIP the next N probe /metrics refreshes
                        (fires through :func:`consume`, consuming one
                        count per skipped refresh): the replica stays
                        healthy and routable but its /fleet/metrics
                        body goes STALE — the staleness stamping
                        (scrape_age_seconds) must flag it and judges
                        must treat the body as missing

Migration fault points (serving/migrate.py + serving/server.py;
call-point style — ``@N`` counts CALLS; exercised by
tests/test_migrate.py):

  ``migrate_corrupt`` / ``migrate_corrupt@N``
                        flip one byte of the Nth upcoming exported
                        page image AFTER its CRC32 is stamped (fires
                        through :func:`consume`): the import side's
                        checksum verify must convict the transfer
                        (typed MigratePayloadError), the migration
                        fails counted, and the router falls back to
                        resume-by-replay — the request still succeeds
                        and garbage KV is never attended
  ``migrate_hang`` / ``migrate_hang@N``
                        stall the Nth upcoming slot-state export for
                        ``DTX_MIGRATE_HANG_S`` seconds (default 2.0)
                        — a slow/stuck transfer; the drain path's
                        total transfer budget (serving/retry.py
                        deadline) must bound it and fall back typed

Control-plane fault points (tools/autoscaler.py + serving/engine.py;
exercised by tests/test_autoscaler.py):

  ``scale_flap@T`` / ``scale_flap@A-B``
                        oscillate the autoscaler's observed capacity
                        signal on those control TICKS (alternating
                        extreme-high / extreme-low burn by tick
                        parity): hysteresis + cooldowns must hold the
                        replica count steady. NOT one-shot — arm a
                        range for a sustained flap window.
  ``canary_regress``    persistent per-iteration step-time penalty
                        (``DTX_CANARY_REGRESS_S`` seconds, default
                        0.05) injected at the top of every engine
                        step while armed — a deliberately
                        perf-regressed canary build; the canary judge
                        must auto-roll-back unattended. Armed on ONE
                        replica via its DTX_FAULTS env.

Armed from the ``DTX_FAULTS`` environment variable on first use (env
crosses the supervisor's subprocess boundary) and/or programmatically
via :func:`arm` (``TrainConfig.faults`` feeds this). One-shot kinds
(raise/sigterm/sigkill/corrupt_params/ckpt_write) disarm after firing
so a resumed run that replays the same step does not re-fire in
process; across processes the supervisor strips ``DTX_FAULTS`` from the
child environment on restarts (tools/train_supervisor.py).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional, Set

ENV_VAR = "DTX_FAULTS"
HANG_ENV_VAR = "DTX_SERVE_HANG_S"
CKPT_HANG_ENV_VAR = "DTX_CKPT_HANG_S"
ROUTER_HANG_ENV_VAR = "DTX_ROUTER_HANG_S"
TRAIN_HANG_ENV_VAR = "DTX_TRAIN_HANG_S"
SKEW_ENV_VAR = "DTX_SKEW_S"
TIER_HANG_ENV_VAR = "DTX_TIER_HANG_S"
CANARY_REGRESS_ENV_VAR = "DTX_CANARY_REGRESS_S"
MIGRATE_HANG_ENV_VAR = "DTX_MIGRATE_HANG_S"

_STEP_KINDS = (
    "raise", "sigterm", "sigkill", "nan", "corrupt_params",
    # host-loop stall kinds: train_hang is the watchdog's trigger,
    # collective_skew the tolerance case; heartbeat_silence's "step"
    # is a PROCESS INDEX to mute (parallel/heartbeat.py), not a step
    "train_hang", "collective_skew", "heartbeat_silence",
    # serving kinds: steps are ENGINE iterations, not training steps
    "serve_raise", "serve_hang", "serve_corrupt",
    # paged-KV kinds (serving/pages.py): typed pool exhaustion and
    # cached-prefix poisoning, same engine-iteration counting
    "page_exhaust", "prefix_corrupt",
    # speculative-decoding kinds (serving/spec.py): drafter-pool
    # poison (one-shot) and the persistent 0%-acceptance storm
    "spec_drafter_crash", "spec_reject_storm",
    # structured-decoding kind (serving/constrain.py): dead-end-sentinel
    # poison of one constrained slot's FSM cursor
    "constrain_dead_end",
    # host-tier kinds (serving/host_tier.py): demotion capture failure,
    # promotion stall-then-fail, and stash corruption before swap-in
    "page_demote_fail", "page_promote_hang", "page_swap_corrupt",
    # autoscaler kind (tools/autoscaler.py): "step" is a control TICK;
    # armed ticks see an oscillating capacity signal (not one-shot)
    "scale_flap",
    # model-quality kinds (obs/quality.py): a silent params drift only
    # the quality fingerprint catches, and a NaN telemetry tail that
    # must degrade to "no signal" rather than crash the step or judge
    "quality_drift", "quality_nan",
)
_POINT_KINDS = (
    "ckpt_write", "ckpt_fsync", "ckpt_manifest", "ckpt_gc",
    # stall-class point: fires through stall() (sleeps), not check()
    "ckpt_hang",
    # router points (serving/router.py): probe/pick fire through
    # check(), replica_hang through stall()
    "router_probe_fail", "router_pick_raise", "router_replica_hang",
    # constraint-compile point (serving/constrain.py:compile_constraint)
    "constrain_compile_fail",
    # staleness point (serving/router.py): consume() skips the next N
    # probe metrics refreshes instead of raising
    "router_stale_metrics",
    # persistent engine-step penalty (serve_fire): a deliberately
    # perf-regressed canary build; membership-checked, never consumed
    "canary_regress",
    # live-migration points (serving/migrate.py): corrupt fires through
    # consume() (flip a byte post-checksum), hang through stall()
    "migrate_corrupt", "migrate_hang",
)


class FaultInjected(RuntimeError):
    """The injected failure (distinguishable from organic errors)."""


_plan: Optional[dict] = None  # lazy; see _get()


def _parse_steps(expr: str) -> Set[int]:
    if "-" in expr:
        a, b = expr.split("-", 1)
        return set(range(int(a), int(b) + 1))
    return {int(expr)}


def _parse(spec: str) -> dict:
    plan = {k: set() for k in _STEP_KINDS}
    plan["points"] = {}  # point -> calls remaining until it fires
    for token in spec.split(","):
        token = token.strip()
        if not token:
            continue
        kind, _, arg = token.partition("@")
        if kind in _STEP_KINDS:
            if not arg:
                raise ValueError(f"fault {kind!r} needs @step (got {token!r})")
            plan[kind] |= _parse_steps(arg)
        elif kind in _POINT_KINDS:
            plan["points"][kind] = int(arg) if arg else 1
        else:
            raise ValueError(
                f"unknown fault kind {kind!r} in {token!r}; known: "
                f"{_STEP_KINDS + _POINT_KINDS}"
            )
    return plan


def _get() -> dict:
    global _plan
    if _plan is None:
        _plan = _parse(os.environ.get(ENV_VAR, ""))
    return _plan


def arm(spec: Optional[str]) -> None:
    """Merge a spec into the armed plan (env faults stay armed)."""
    if not spec:
        _get()
        return
    extra = _parse(spec)
    plan = _get()
    for k in _STEP_KINDS:
        plan[k] |= extra[k]
    plan["points"].update(extra["points"])


def reset() -> None:
    """Disarm everything (tests); env re-arms lazily on next use."""
    global _plan
    _plan = None
    if ENV_VAR in os.environ:  # a stale env spec must not re-arm
        _plan = _parse("")


def armed() -> bool:
    p = _get()
    return bool(p["points"]) or any(p[k] for k in _STEP_KINDS)


def fire(step: int) -> None:
    """Crash-class faults for this iteration; called at the top of the
    train loop. raise/sigterm are one-shot; sigkill needs no disarm."""
    p = _get()
    if step in p["raise"]:
        p["raise"].discard(step)
        raise FaultInjected(f"injected crash at iteration {step}")
    if step in p["sigterm"]:
        p["sigterm"].discard(step)
        os.kill(os.getpid(), signal.SIGTERM)
    if step in p["sigkill"]:
        os.kill(os.getpid(), signal.SIGKILL)


def serve_fire(iteration: int) -> None:
    """Crash-class serving faults for this ENGINE iteration; called at
    the top of ``ServingEngine.step``. ``serve_raise`` is one-shot (a
    supervised restart replaying the same iteration number must not
    re-crash); ``serve_hang`` stalls the step long enough for the
    wall-time watchdog to flag the engine degraded, then disarms.
    ``canary_regress`` is deliberately PERSISTENT — every iteration
    pays the injected step-time penalty while it stays armed (a
    regressed build does not heal itself); the canary judge's
    auto-rollback is what ends it."""
    p = _get()
    if iteration in p["serve_raise"]:
        p["serve_raise"].discard(iteration)
        raise FaultInjected(
            f"injected engine crash at iteration {iteration}"
        )
    if iteration in p["serve_hang"]:
        p["serve_hang"].discard(iteration)
        time.sleep(float(os.environ.get(HANG_ENV_VAR, "2.0")))
    if "canary_regress" in p["points"]:
        time.sleep(float(os.environ.get(CANARY_REGRESS_ENV_VAR, "0.05")))


def serve_corrupt_at(iteration: int) -> bool:
    """One-shot slot-corruption fault: when armed for this engine
    iteration, the engine NaN-poisons one occupied slot's KV rows."""
    p = _get()
    if iteration in p["serve_corrupt"]:
        p["serve_corrupt"].discard(iteration)
        return True
    return False


def page_exhaust_at(iteration: int) -> bool:
    """One-shot paged-pool exhaustion fault: when armed for this engine
    iteration, the engine forces the page pool's next admission plan to
    raise the typed :class:`~serving.pages.PagePoolExhaustedError`
    (surfaced as the 503 shed path)."""
    p = _get()
    if iteration in p["page_exhaust"]:
        p["page_exhaust"].discard(iteration)
        return True
    return False


def prefix_corrupt_at(iteration: int) -> bool:
    """One-shot cached-prefix poison fault: when armed for this engine
    iteration, the engine NaN-poisons one radix-cached prefix page —
    the finite-logits guard (not garbage tokens) must catch it."""
    p = _get()
    if iteration in p["prefix_corrupt"]:
        p["prefix_corrupt"].discard(iteration)
        return True
    return False


def spec_drafter_crash_at(iteration: int) -> bool:
    """One-shot drafter-pool poison fault: when armed for this engine
    iteration, the engine NaN-poisons the speculative drafter's KV
    pool — the drafter's finite-logits guard (not garbage proposals)
    must catch it and fall back to non-spec decode."""
    p = _get()
    if iteration in p["spec_drafter_crash"]:
        p["spec_drafter_crash"].discard(iteration)
        return True
    return False


def spec_reject_storm_at(iteration: int) -> bool:
    """Whether the fused verify step must reject EVERY drafted token
    at this engine iteration. Deliberately NOT one-shot — arm a range
    (``spec_reject_storm@A-B``) for a sustained storm; the throughput
    floor under it is the non-spec rate."""
    return iteration in _get()["spec_reject_storm"]


def constrain_dead_end_at(iteration: int) -> bool:
    """One-shot constraint dead-end fault: when armed for this engine
    iteration, the engine plants the dead-end sentinel (fsm_state -1)
    on one constrained ACTIVE slot — the zero-mask sweep must retire
    it typed (finish_reason "constraint_dead_end"), never hang or
    emit through an all-zero mask."""
    p = _get()
    if iteration in p["constrain_dead_end"]:
        p["constrain_dead_end"].discard(iteration)
        return True
    return False


def page_demote_fail_at(iteration: int) -> bool:
    """One-shot demotion-failure fault: when armed for this engine
    iteration, the engine SKIPS capturing the drained demotion plans'
    device bytes — the evicted prefixes are lost from the tier (typed,
    counted) and later requests recompute them. One-shot."""
    p = _get()
    if iteration in p["page_demote_fail"]:
        p["page_demote_fail"].discard(iteration)
        return True
    return False


def page_promote_hang_at(iteration: int) -> bool:
    """One-shot promotion-stall fault: when armed for this engine
    iteration, the engine sleeps ``DTX_TIER_HANG_S`` seconds (default
    2.0) and then FAILS the admission's promotions — the recompute
    fallback (cached length truncated to the device prefix) must kick
    in, typed and counted, never a wedge."""
    p = _get()
    if iteration in p["page_promote_hang"]:
        p["page_promote_hang"].discard(iteration)
        time.sleep(float(os.environ.get(TIER_HANG_ENV_VAR, "2.0")))
        return True
    return False


def page_swap_corrupt_at(iteration: int) -> bool:
    """One-shot swap-corruption fault: when armed for this engine
    iteration, the engine flips one byte of a stashed page image
    before injecting it — the CRC32 verify must detect it and degrade
    to a bit-exact full restart, never inject garbage KV."""
    p = _get()
    if iteration in p["page_swap_corrupt"]:
        p["page_swap_corrupt"].discard(iteration)
        return True
    return False


def quality_drift_at(iteration: int) -> bool:
    """One-shot silent-drift fault: when armed for this engine
    iteration, the engine perturbs its params (λ for the diff
    families, an argmax-preserving logit rescale for control) — logits
    stay finite and fast, so only the quality fingerprint's PSI score
    can flag the replica. The perturbation persists until restart."""
    p = _get()
    if iteration in p["quality_drift"]:
        p["quality_drift"].discard(iteration)
        return True
    return False


def quality_nan_at(iteration: int) -> bool:
    """One-shot telemetry-poison fault: when armed for this engine
    iteration, the engine replaces that iteration's host-side quality
    signals with NaN — the "no signal" degradation contract
    (obs/quality.py) must skip them, never crash or score drift."""
    p = _get()
    if iteration in p["quality_nan"]:
        p["quality_nan"].discard(iteration)
        return True
    return False


def train_stall(step: int) -> None:
    """Host-loop stall faults for this training iteration; called just
    after the watchdog arms (train/trainer.py) so the stall lands
    INSIDE the armed window. ``train_hang`` sleeps long enough
    (``DTX_TRAIN_HANG_S``, default 30 s) that a sane step deadline
    fires first; ``collective_skew`` sleeps briefly (``DTX_SKEW_S``,
    default 0.5 s) — ordinary straggler skew the watchdog must ride
    out. Both one-shot."""
    p = _get()
    if step in p["train_hang"]:
        p["train_hang"].discard(step)
        time.sleep(float(os.environ.get(TRAIN_HANG_ENV_VAR, "30.0")))
    if step in p["collective_skew"]:
        p["collective_skew"].discard(step)
        time.sleep(float(os.environ.get(SKEW_ENV_VAR, "0.5")))


def scale_flap_at(tick: int) -> bool:
    """Whether the autoscaler's observed capacity signal must OSCILLATE
    at this control tick (``scale_flap@A-B``). Deliberately NOT
    one-shot — a flap window spans many ticks; hysteresis + cooldowns
    are what must hold the fleet steady through it."""
    return tick in _get()["scale_flap"]


def canary_regress_armed() -> bool:
    """Whether the persistent canary step-time penalty is armed (the
    judge/test side can ask without paying the sleep)."""
    return "canary_regress" in _get()["points"]


def heartbeat_silenced(process_index: int) -> bool:
    """Whether heartbeat publications from this process index are muted
    (``heartbeat_silence@P``). Deliberately NOT one-shot — a partitioned
    host stays silent until something kills it."""
    return process_index in _get()["heartbeat_silence"]


def nan_armed() -> bool:
    """Whether any NaN-poison steps are armed — when true the trainer
    threads a poison scale through EVERY step so the batch pytree
    structure (and therefore the compiled program) never changes."""
    return bool(_get()["nan"])


def poison_at(step: int) -> bool:
    return step in _get()["nan"]


def corrupt_params_at(step: int) -> bool:
    p = _get()
    if step in p["corrupt_params"]:
        p["corrupt_params"].discard(step)
        return True
    return False


def check(point: str) -> None:
    """Call-point fault (e.g. ``ckpt_write``): raises on the armed call."""
    points = _get()["points"]
    if point not in points:
        return
    points[point] -= 1
    if points[point] <= 0:
        del points[point]
        raise FaultInjected(f"injected failure at {point}")


def consume(point: str) -> bool:
    """Consuming call-point fault (``router_stale_metrics@N``): each
    armed call returns True AND spends one count — the fault fires on
    the next N calls, then disarms. The inverse budget shape from
    :func:`check` (which fires ONCE, on the Nth call): use this for
    "the next N occurrences misbehave" windows."""
    points = _get()["points"]
    if point not in points:
        return False
    points[point] -= 1
    if points[point] <= 0:
        del points[point]
    return True


def stall(point: str) -> None:
    """Stall-class call-point fault (``ckpt_hang``,
    ``router_replica_hang``, ``migrate_hang``): the armed call SLEEPS
    instead of raising — a slow disk / hung replica, not a broken one.
    The sleep length comes from ``DTX_ROUTER_HANG_S`` for ``router_*``
    points, ``DTX_MIGRATE_HANG_S`` for ``migrate_*`` points, and
    ``DTX_CKPT_HANG_S`` otherwise (default 2.0 s). Same ``@N``
    call-counting as :func:`check`."""
    points = _get()["points"]
    if point not in points:
        return
    points[point] -= 1
    if points[point] <= 0:
        del points[point]
        if point.startswith("router_"):
            env = ROUTER_HANG_ENV_VAR
        elif point.startswith("migrate_"):
            env = MIGRATE_HANG_ENV_VAR
        else:
            env = CKPT_HANG_ENV_VAR
        time.sleep(float(os.environ.get(env, "2.0")))
