"""Tracing and throughput counters.

The reference's only observability is loss prints and GPU-memory numbers
(train.py:148,288,293; SURVEY.md section 5.1) — it has no profiler
integration and never measures tokens/sec, even though that is the
north-star metric (BASELINE.json). Here both are native:

  - ``trace(logdir)`` wraps ``jax.profiler`` so any code region can be
    captured and viewed in TensorBoard/Perfetto (XLA op-level timeline,
    HBM usage, fusion boundaries),
  - ``ProfilerWindow`` captures a fixed window of training iterations —
    the trainer drives it from the hot loop,
  - ``Throughput`` computes rolling tokens/sec between metric logs; the
    trainer attaches it to every log_step record.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a jax.profiler trace of the enclosed region into
    ``logdir`` (inspect with TensorBoard's profile plugin or Perfetto)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class ProfilerWindow:
    """Capture iterations [start, start+n) of a training loop.

    Handles the edge cases an inline start/stop pair gets wrong: resuming
    from a checkpoint past the window start (never calls stop without a
    matching start) and loops that end inside the window (``close()``
    finalizes the trace so it is never left running/unwritten).
    """

    def __init__(self, logdir: Optional[str], start: int, n_steps: int = 5):
        self.logdir = logdir
        self.start = start
        self.stop = start + n_steps
        self.active = False

    def step(self, iter_num: int, sync=None) -> None:
        """Call once per loop iteration with the post-increment iteration
        number; ``sync`` (any jax value) is blocked on before finalizing
        so the trace covers completed device work."""
        if not self.logdir:
            return
        if not self.active and iter_num == self.start:
            jax.profiler.start_trace(self.logdir)
            self.active = True
        elif self.active and iter_num >= self.stop:
            self._finalize(sync)

    def close(self, sync=None) -> None:
        """Finalize if the loop ended while the window was open."""
        if self.active:
            self._finalize(sync)

    def _finalize(self, sync) -> None:
        if sync is not None:
            jax.block_until_ready(sync)
        jax.profiler.stop_trace()
        self.active = False
        print(f"Profiler trace written to {self.logdir}")


class Throughput:
    """Rolling tokens/sec between ``update`` calls.

    ``update(total_tokens)`` takes the cumulative token count and returns
    the rate since the previous call (None on the first call, when there
    is no interval yet). Wall-clock based, so it reflects everything the
    user waits for: device compute, host input pipeline, and dispatch.
    (bench.py's headline number is measured separately over an explicitly
    synced loop — this class is the trainer's rolling in-run view.)
    """

    def __init__(self) -> None:
        self._last_t: Optional[float] = None
        self._last_tokens = 0

    def update(self, total_tokens: int) -> Optional[float]:
        now = time.perf_counter()
        rate = None
        if self._last_t is not None and now > self._last_t:
            rate = (total_tokens - self._last_tokens) / (now - self._last_t)
        self._last_t = now
        self._last_tokens = total_tokens
        return rate
