"""Configuration dataclasses.

Replaces the reference's single ``TrainingConfig`` (train.py:57-93) with an
explicit model/train split and a real ``model`` switch instead of the
reference's comment-toggled model selection (train.py:205-230).

Reference landmines deliberately fixed here (SURVEY.md section 5.6):
  - ``n_terms`` is a real typed field (train.py:79 lacks an annotation, so
    it silently becomes a class attribute and is dropped from ``vars()``),
  - ``batch_size`` is not carried as a dead field (train.py:67 declares it
    but only ``micro_batch_size`` is ever used),
  - no global-config access from helper functions (train.py:36).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

MODEL_KINDS = ("control", "diff", "ndiff")


@dataclass(frozen=True)
class ModelConfig:
    """Hyperparameters shared by all three model families.

    Mirrors the constructor surface of the reference models
    (control.py:114, diff_transformer.py:129, Ndiff_transformer.py:183).
    """

    model: str = "control"  # one of MODEL_KINDS (train.py:205-230 switch)
    vocab_size: int = 12000  # train.py:41 (BPE vocab)
    n_embd: int = 768  # train.py:60
    n_head: int = 4  # train.py:61; the *diff* head count
    n_layer: int = 8  # train.py:62
    block_size: int = 512  # train.py:63
    dropout: float = 0.0  # train.py:64
    n_terms: int = 4  # Ndiff_transformer.py:183 default (train.py's 0 is a bug)
    # TPU execution policy (no reference analog; reference used CUDA AMP fp16,
    # train.py:251-279 — on TPU we use bf16 compute without loss scaling).
    compute_dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # Attention backend: "xla" (merged-head einsum under jit) or "pallas"
    # (fused differential flash attention kernel).
    attention_impl: str = "xla"
    # FFN/norm backend — the non-attention hot path. "xla": the reference
    # composition (ops/swiglu.py + ops/norms.py as separate XLA ops).
    # "pallas": the fused kernels — residual-add + LayerNorm in one pass
    # at every block boundary (ops/fused_norm_residual.py, GroupLayerNorm
    # included) and the SwiGLU chain (gate/xform matmuls -> SiLU ->
    # product, optionally with the pre-LN fused in front) as one Pallas
    # kernel with a fused backward (ops/fused_ffn.py). Selected exactly
    # like attention_impl, for all three model families and the decode
    # path; interpret-mode on CPU.
    ffn_impl: str = "xla"
    # Decode-side (serving / generate_cached) attention backend for the
    # single-query step over the ring KV cache: "xla" keeps the plain
    # einsum+softmax composition (models/decode.py), "pallas" routes the
    # batched L=1 step through the fused online-softmax kernel
    # (ops/decode_attention.py: per-stream softmaxes + lambda combine in
    # one pass; score maps never reach HBM). Selected exactly like
    # attention_impl/ffn_impl; interpret-mode on CPU. Prefill chunks
    # always run the XLA chunk path (compute-bound, not the decode
    # bottleneck).
    decode_attention_impl: str = "xla"
    # KV-cache storage dtype for the ring/slot-pool caches
    # (models/decode.py init_cache): "auto" stores compute_dtype (the
    # pre-quantization behavior), "bf16" forces bfloat16 storage, "int8"
    # stores symmetric per-head-scale int8 K/V (ops/decode_attention.py
    # quantize_kv) — about half the bf16 HBM bytes per slot, so ~2x
    # concurrent slot capacity at equal HBM, with dequantization fused
    # into the Pallas kernel's tile loads (the XLA path dequantizes the
    # cache row before attending). bf16/auto decode is bit-identical
    # between impls at the greedy level; int8 is tolerance-gated
    # (tests/test_decode_attention.py).
    kv_cache_dtype: str = "auto"
    # Sequence-parallel strategy when the mesh's sequence axis is > 1:
    # "ring" (K/V rotation with O(Tl) chunk memory, parallel/ring.py) or
    # "ulysses" (all-to-all head/sequence re-sharding so the unmodified
    # full-T flash kernel runs per head slice, parallel/ulysses.py).
    sequence_impl: str = "ring"
    # Rematerialize each transformer block on the backward pass
    # (jax.checkpoint): trades ~1/3 more FLOPs for O(n_layer) less
    # activation memory — the standard TPU lever for bigger micro-batches
    # or longer contexts (no reference analog; it keeps all activations).
    remat: bool = False
    # What jax.checkpoint may SAVE per block when remat is on — the
    # per-layer-group recompute policy (models/common.py REMAT_POLICIES):
    #   "none"       jax.checkpoint's default: save only block inputs,
    #                recompute everything (max memory savings),
    #   "dots"       save matmul outputs (checkpoint_policies.dots_
    #                saveable): skips recomputing the MXU-bound work,
    #                recomputes only the cheap elementwise/norm chain —
    #                the sweet spot once the FFN epilogue is fused
    #                (fused kernels make the recompute side cheaper, so
    #                the policy trade-off moved; sweep with
    #                tools/ffn_sweep.py --remat-policies),
    #   "dots_no_batch"  dots_with_no_batch_dims_saveable (Flax's
    #                default "save the small stuff" policy),
    #   "nothing"    nothing_saveable, explicit,
    #   "everything" everything_saveable (remat becomes a no-op marker).
    remat_policy: str = "none"
    # Fused chunked linear+cross-entropy (ops/losses.py): when set, the
    # training loss never materializes the (B, T, V) logits — it scans
    # position-chunks of this size through the lm head with a
    # recompute-backward. The long-context companion to the flash kernels
    # (the full logits tensor, not attention, is the memory wall once
    # flash is on). forward() then returns (None, loss) when targets are
    # given. None = dense loss (the reference's shape, control.py:153-159).
    loss_chunk: Optional[int] = None

    def __post_init__(self):
        if self.model not in MODEL_KINDS:
            raise ValueError(f"model must be one of {MODEL_KINDS}, got {self.model!r}")
        if self.attention_impl not in ("xla", "pallas"):
            raise ValueError(
                "attention_impl must be 'xla' or 'pallas', got "
                f"{self.attention_impl!r}"
            )
        if self.ffn_impl not in ("xla", "pallas"):
            raise ValueError(
                f"ffn_impl must be 'xla' or 'pallas', got {self.ffn_impl!r}"
            )
        if self.decode_attention_impl not in ("xla", "pallas"):
            raise ValueError(
                "decode_attention_impl must be 'xla' or 'pallas', got "
                f"{self.decode_attention_impl!r}"
            )
        if self.kv_cache_dtype not in ("auto", "bf16", "int8"):
            raise ValueError(
                "kv_cache_dtype must be one of auto|bf16|int8, got "
                f"{self.kv_cache_dtype!r}"
            )
        if self.remat_policy not in (
            "none", "dots", "dots_no_batch", "nothing", "everything"
        ):
            raise ValueError(
                "remat_policy must be one of none|dots|dots_no_batch|"
                f"nothing|everything, got {self.remat_policy!r}"
            )
        if self.sequence_impl not in ("ring", "ulysses"):
            raise ValueError(
                "sequence_impl must be 'ring' or 'ulysses', got "
                f"{self.sequence_impl!r}"
            )
        if self.loss_chunk is not None and self.loss_chunk < 1:
            raise ValueError(f"loss_chunk must be positive, got {self.loss_chunk}")
        if self.model == "ndiff" and self.n_terms < 1:
            raise ValueError(
                "n_terms must be >= 1 (the reference's n_terms=0 config, "
                "train.py:79, would crash at Ndiff_transformer.py:119)"
            )

    @property
    def head_size(self) -> int:
        """Per-head query/key width.

        control.py:96 uses n_embd // n_head; the differential variants halve
        it because each head carries a doubled value
        (diff_transformer.py:111, Ndiff_transformer.py:164).
        """
        if self.model == "control":
            return self.n_embd // self.n_head
        return self.n_embd // (self.n_head * 2)

    @property
    def value_size(self) -> int:
        """Per-head value width: doubled for differential variants
        (diff_transformer.py:30, Ndiff_transformer.py:59)."""
        if self.model == "control":
            return self.head_size
        return self.head_size * 2

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class ServingConfig:
    """Continuous-batching inference engine knobs (serving/engine.py).

    The engine holds a fixed pool of ``num_slots`` KV-cache slots (one
    per in-flight sequence) and runs one iteration per step: admit queued
    requests into free slots, advance prefill by at most
    ``prefill_budget`` prompt tokens (in power-of-two chunks no larger
    than ``prefill_chunk``), then decode every active slot as one batched
    length-1 chunk. All shapes are static — slot count, chunk ladder and
    RoPE table length are fixed at engine build — so admissions and
    retirements never recompile (Orca-style iteration-level scheduling
    over a vLLM-style slot pool; no reference analog).
    """

    # Fixed decode batch = KV slot pool size. Memory scales linearly:
    # each slot owns a full (n_layer, S, block_size) K/V ring.
    num_slots: int = 8
    # Largest single prefill chunk (tokens). Prompts are split into
    # descending power-of-two chunks <= this, so at most
    # log2(prefill_chunk)+1 prefill shapes ever compile.
    prefill_chunk: int = 128
    # Max prompt tokens prefilled per engine iteration, across all
    # admissions (FCFS). Bounds how long a burst of long prompts can
    # stall decoding sequences — Orca's iteration-level fairness knob.
    prefill_budget: int = 256
    # RoPE table length = hard cap on prompt + generated tokens for the
    # RoPE families (control/ndiff), which may roll past block_size on
    # the ring cache. 0 = block_size (in-window only). The diff family's
    # learned absolute position table cannot roll (models/decode.py), so
    # it is always capped at block_size regardless of this value.
    max_seq_len: int = 0
    # Default stop token; a request's SamplingParams.eos_token_id
    # overrides. None = length-only termination (the reference has no
    # EOS concept in generation, control.py:163-171).
    eos_token_id: Optional[int] = None
    # Admission bound: submissions past this many WAITING requests (not
    # yet holding a slot) are rejected immediately with QueueFullError
    # (HTTP 503 from /generate) instead of growing the wait queue — and
    # the caller's latency — without limit. 0 = unbounded (the
    # pre-bound behavior).
    max_queue_len: int = 0
    # Default server-side deadline (seconds from submission) applied to
    # requests that do not carry their own. Expired requests are shed at
    # admission and retired mid-decode (KV slot reclaimed) with a typed
    # DeadlineExceededError instead of decoding for a caller that has
    # already given up. 0 = no default deadline.
    default_deadline_s: float = 0.0
    # Graceful-drain budget: drain() stops admission (HTTP 503 +
    # Retry-After), then waits this long for in-flight requests to
    # finish before force-failing the stragglers and shutting down.
    drain_timeout_s: float = 30.0
    # Engine supervision (serving/server.py:EngineRunner): a crashed
    # engine step fails its in-flight requests with EngineCrashError,
    # rebuilds the slot pool from params, and resumes — up to this many
    # restarts per runner lifetime, each preceded by an exponential
    # backoff (restart_backoff_s * 2^n, capped at
    # restart_backoff_max_s). Budget exhausted = the runner fails hard.
    max_restarts: int = 3
    restart_backoff_s: float = 0.5
    restart_backoff_max_s: float = 30.0
    # Watchdog: a decode iteration exceeding this wall-time budget marks
    # the engine "degraded" on /health (it cannot be interrupted — the
    # device call is synchronous — but operators/load-balancers can
    # route around it). 0 = watchdog off.
    step_time_budget_s: float = 0.0
    # Continuous on-device profiling (obs/device_profile.py): every
    # this-many engine iterations, wrap ONE iteration in a
    # jax.profiler capture, parse it off-loop, and publish the
    # per-kernel step decomposition as device_* gauges on /metrics,
    # {"record":"device_profile"} JSONL rows, and a stitchable
    # device-lane Chrome trace — all under <profile_dir>. Uncaptured
    # iterations pay one integer compare; the decode compile count
    # stays 1 (capture wraps an already-compiled step). 0 = off.
    profile_every: int = 0
    profile_dir: str = "device_profiles"
    # Serving-side overrides of the corresponding ModelConfig knobs,
    # applied by ServingEngine at build: a checkpoint trained with the
    # defaults can still serve with the fused decode kernel / quantized
    # KV without editing its saved model config. "" = inherit the
    # ModelConfig value.
    decode_attention_impl: str = ""
    kv_cache_dtype: str = ""
    # Paged KV cache (serving/pages.py). 0 = the legacy contiguous
    # per-slot rings. > 0 = the slot pool stores KV in fixed pages of
    # this many tokens (must divide block_size), mapped through
    # per-slot page tables that ride the ONE jitted decode step as
    # runtime arrays — zero recompiles as pages churn. Admission then
    # keys on FREE PAGES, not slots: short requests reserve only the
    # pages they can ever write, so capacity stops scaling with
    # worst-case context.
    kv_page_size: int = 0
    # Total physical pages in the pool (one reserved trash page is
    # added on top). 0 = auto: num_slots * (block_size / kv_page_size)
    # + prefix_cache_pages — the contiguous-equivalent footprint.
    # Sizing BELOW auto is the capacity lever: 2x num_slots over the
    # same pages serves 2x concurrent short-context requests at equal
    # HBM (admission sheds to the queue when pages run out).
    kv_pool_pages: int = 0
    # Radix-tree shared-prefix reuse (serving/pages.py): retired
    # prompts donate their KV pages to a refcounted radix tree;
    # requests sharing a cached prefix skip its prefill (near-zero
    # TTFT) and fork copy-on-write at partial-page boundaries.
    # Unreferenced prefixes are LRU-evicted under page pressure.
    # Only meaningful with kv_page_size > 0.
    prefix_cache: bool = True
    # Extra pool pages added on top of the auto sizing as cached-
    # prefix headroom, so a fully-loaded slot pool still keeps hot
    # system prompts resident instead of thrashing them.
    prefix_cache_pages: int = 0
    # Speculative decoding (serving/spec.py). "" = off. "ngram" = the
    # drafter-free prompt-lookup fallback (a host-side suffix map over
    # each request's prompt + emitted tokens proposes continuations);
    # "model" = a small drafter checkpoint (spec_drafter_ckpt —
    # typically the control family beside a diff/ndiff target; any
    # family sharing the tokenizer works) run on its own slot-pool KV
    # cache. Either way the target verifies k drafted tokens in ONE
    # fused multi-row pool step (models/decode.py:forward_decode_spec)
    # with a fused accept/reject: greedy requests accept on argmax
    # match (bit-identical to non-spec greedy), sampled requests run
    # the Leviathan et al. 2023 acceptance-ratio test under the
    # existing fold_in per-request key chains.
    spec_mode: str = ""
    # Draft tokens proposed per slot per iteration (the k in the fused
    # k+1-row verify). k is baked into a fixed ladder {0, spec_draft_len}
    # of compiled step shapes; PER-REQUEST draft lengths (admission
    # caps, SamplingParams.draft_len, window clamps) ride as runtime
    # arrays, so mixed spec/non-spec traffic never recompiles.
    spec_draft_len: int = 4
    # Drafter checkpoint dir for spec_mode == "model", loaded beside
    # the target's params via load_params_for_inference (manifest
    # verification and int8 weight quantization apply to it too).
    spec_drafter_ckpt: str = ""
    # Verify-step formulation (models/decode.py:forward_decode_spec).
    # "exact" (default): a static unroll of k+1 engine-native L=1
    # sub-steps in one jitted program — every matmul keeps the plain
    # decode step's shapes, so greedy spec output is bit-identical to
    # non-spec decoding at ANY model size. "batched": all rows in one
    # pass through the fused multi-query decode-attention kernel (each
    # slot's KV ring/pages streamed ONCE for all k+1 rows — the
    # bandwidth-optimal TPU formulation); large-contraction XLA
    # matmuls may reassociate reductions vs the 1-row step, so greedy
    # ties can resolve differently at scale (bit-identical at the
    # pinned test sizes; sampled distribution unchanged).
    spec_verify: str = "exact"
    # Structured decoding (serving/constrain.py). Cap on the top-N
    # alternatives a request may ask to echo per token
    # (SamplingParams.logprobs) — N is baked into the jitted sampler's
    # output packing, so the cap is the compile-time K and per-request
    # values <= K ride as runtime truncation.
    max_logprobs: int = 5
    # Compiled-constraint cache capacity (distinct FSMs held,
    # refcounted like radix prefixes; refcount-0 entries LRU-evict
    # past this bound). Entries are host numpy tables — bytes show on
    # /metrics as serving_constraint_cache_bytes.
    constraint_cache_entries: int = 32
    # Host-RAM KV page tier (serving/host_tier.py). 0 = off. > 0 =
    # evicted full radix pages DEMOTE into pinned host buffers up to
    # this many bytes (own LRU) instead of vanishing, and admissions
    # matching a demoted prefix PROMOTE it back with a host->device
    # copy — never a recompute. Also enables mid-decode preemption:
    # a lower class's pages stash here and resume bit-exact. int8
    # pages (~0.53x bf16 bytes) make a few GB hold ~50x the HBM pool.
    # Only meaningful with kv_page_size > 0.
    host_tier_bytes: int = 0
    # Anti-starvation aging for priority scheduling: a queued request's
    # effective rank improves by one class per this many seconds
    # waited, so saturating high-priority traffic cannot starve the
    # batch class forever. 0 = no aging (strict class order).
    priority_aging_s: float = 10.0
    # Per-class concurrent-slot bounds, "class:N,class:N" (classes from
    # serving/request.py:PRIORITY_CLASSES). A class at its bound stops
    # admitting until one of its slots retires — e.g. "batch:2" keeps
    # bulk traffic from occupying the whole pool. "" = no bounds.
    priority_max_slots: str = ""
    # Model-quality telemetry (obs/quality.py). When on, the jitted
    # sample/verify steps append a fixed-shape per-slot quality vector
    # (sampled-distribution entropy, top-1 logit margin, repetition
    # flag — models/decode.py:quality_vector) to their packed outputs:
    # runtime arrays only, so the decode compile count stays 1 and
    # telemetry-OFF output stays bit-identical to the pre-quality
    # layout. The engine folds the signals into serving_token_entropy/
    # serving_logit_margin histograms, per-request
    # RequestOutput.quality stats, per-layer serving_lambda_mean
    # gauges (ops/lambdas.py path), and the serving_quality_drift
    # gauge vs the reference fingerprint below.
    quality_telemetry: bool = False
    # Path to a reference quality fingerprint JSON (recorded from a
    # known-good window via ``--quality-record``): live entropy/margin
    # sketches are compared against it with a PSI-style drift score
    # exposed as serving_quality_drift. "" = no reference (drift 0).
    quality_fingerprint: str = ""

    def __post_init__(self):
        if self.decode_attention_impl not in ("", "xla", "pallas"):
            raise ValueError(
                "decode_attention_impl must be ''|'xla'|'pallas', got "
                f"{self.decode_attention_impl!r}"
            )
        if self.kv_cache_dtype not in ("", "auto", "bf16", "int8"):
            raise ValueError(
                "kv_cache_dtype must be ''|auto|bf16|int8, got "
                f"{self.kv_cache_dtype!r}"
            )
        if self.num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {self.num_slots}")
        if self.max_queue_len < 0:
            raise ValueError(
                f"max_queue_len must be >= 0, got {self.max_queue_len}"
            )
        for name in ("default_deadline_s", "drain_timeout_s",
                     "restart_backoff_s", "restart_backoff_max_s",
                     "step_time_budget_s"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}"
                )
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {self.max_restarts}"
            )
        if self.profile_every < 0:
            raise ValueError(
                f"profile_every must be >= 0, got {self.profile_every}"
            )
        if self.prefill_chunk < 1 or (
            self.prefill_chunk & (self.prefill_chunk - 1)
        ):
            raise ValueError(
                f"prefill_chunk must be a positive power of two, got "
                f"{self.prefill_chunk}"
            )
        if self.prefill_budget < 1:
            raise ValueError(
                f"prefill_budget must be >= 1, got {self.prefill_budget}"
            )
        if self.max_seq_len < 0:
            raise ValueError(f"max_seq_len must be >= 0, got {self.max_seq_len}")
        for name in ("kv_page_size", "kv_pool_pages", "prefix_cache_pages"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}"
                )
        if self.spec_mode not in ("", "ngram", "model"):
            raise ValueError(
                "spec_mode must be ''|'ngram'|'model', got "
                f"{self.spec_mode!r}"
            )
        if self.spec_mode and self.spec_draft_len < 1:
            raise ValueError(
                f"spec_draft_len must be >= 1 with spec_mode set, got "
                f"{self.spec_draft_len}"
            )
        if self.spec_verify not in ("exact", "batched"):
            raise ValueError(
                "spec_verify must be 'exact'|'batched', got "
                f"{self.spec_verify!r}"
            )
        if self.max_logprobs < 1:
            raise ValueError(
                f"max_logprobs must be >= 1, got {self.max_logprobs}"
            )
        if self.constraint_cache_entries < 1:
            raise ValueError(
                "constraint_cache_entries must be >= 1, got "
                f"{self.constraint_cache_entries}"
            )
        if self.host_tier_bytes < 0:
            raise ValueError(
                f"host_tier_bytes must be >= 0, got {self.host_tier_bytes}"
            )
        if self.priority_aging_s < 0:
            raise ValueError(
                f"priority_aging_s must be >= 0, got "
                f"{self.priority_aging_s}"
            )
        self.priority_slot_bounds()  # validate the spec string eagerly

    def paged(self) -> bool:
        """Whether the engine runs the paged KV-cache subsystem."""
        return self.kv_page_size > 0

    def tiered(self) -> bool:
        """Whether the engine runs the host-RAM page tier (and with it
        mid-decode preemption)."""
        return self.paged() and self.host_tier_bytes > 0

    def priority_slot_bounds(self) -> dict:
        """Parsed ``priority_max_slots``: {class: max concurrent slots}.
        Raises on unknown classes or malformed entries."""
        bounds: dict = {}
        if not self.priority_max_slots:
            return bounds
        valid = ("high", "normal", "batch")
        for part in self.priority_max_slots.split(","):
            part = part.strip()
            if not part:
                continue
            cls, sep, n = part.partition(":")
            cls = cls.strip()
            if not sep or cls not in valid:
                raise ValueError(
                    "priority_max_slots entries must be 'class:N' with "
                    f"class in {valid}, got {part!r}"
                )
            try:
                bound = int(n)
            except ValueError:
                raise ValueError(
                    f"priority_max_slots bound must be an int, got {n!r}"
                )
            if bound < 1:
                raise ValueError(
                    f"priority_max_slots bound must be >= 1, got {bound}"
                )
            bounds[cls] = bound
        return bounds

    def spec_enabled(self) -> bool:
        """Whether the engine runs the speculative-decoding subsystem
        (serving/spec.py)."""
        return bool(self.spec_mode)

    def resolved_pool_pages(self, model: "ModelConfig") -> int:
        """Total physical pages (EXCLUDING the reserved trash page) for
        this model: explicit ``kv_pool_pages`` or the contiguous-
        equivalent auto sizing, plus the prefix-cache headroom."""
        if not self.paged():
            return 0
        if model.block_size % self.kv_page_size:
            raise ValueError(
                f"kv_page_size ({self.kv_page_size}) must divide "
                f"block_size ({model.block_size})"
            )
        per_slot = model.block_size // self.kv_page_size
        base = self.kv_pool_pages or self.num_slots * per_slot
        return base + self.prefix_cache_pages

    def resolved_max_seq_len(self, model: "ModelConfig") -> int:
        """Hard cap on prompt + generated length for this model family."""
        if model.model == "diff":
            return model.block_size
        return max(self.max_seq_len, model.block_size)

    def replace(self, **kw) -> "ServingConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class RouterConfig:
    """Multi-replica router knobs (serving/router.py).

    The router is the fleet-level robustness layer over N single-engine
    replicas: it probes each replica's ``/ready`` + ``/metrics``, spreads
    ``/generate`` traffic with a power-of-two-choices picker over passive
    load scores, fails retriable replies over to a DIFFERENT replica
    under a total per-request deadline, and (optionally) hedges requests
    stuck past a p99-derived latency budget. All knobs are host-side —
    nothing here touches device code or compile caches.
    """

    # -- active health probing ----------------------------------------
    # Seconds between probes of a replica whose last probe succeeded.
    probe_interval_s: float = 0.5
    # Per-probe HTTP timeout (GET /ready, GET /metrics).
    probe_timeout_s: float = 2.0
    # A FAILING replica is probed with exponential backoff: first retry
    # after probe_backoff_s, doubling up to probe_backoff_max_s — a dead
    # host is not hammered at the healthy cadence.
    probe_backoff_s: float = 0.5
    probe_backoff_max_s: float = 10.0
    # Consecutive probe/request transport failures before the replica is
    # EJECTED (never picked, probed on the backoff schedule).
    eject_after: int = 3
    # Slow re-admission: an ejected replica must pass this many
    # consecutive probes before it takes traffic again (a flapping host
    # does not oscillate in and out of rotation on one lucky probe).
    readmit_after: int = 2

    # -- failover / retry ----------------------------------------------
    # Max failover ATTEMPTS per request (first attempt included).
    # Attempts prefer distinct replicas, but when nothing un-tried is
    # eligible a recovered already-tried replica may be re-tried — so
    # on a small fleet this bounds attempts, not distinct replicas.
    max_attempts: int = 3
    # Total per-request wall-clock budget at the router (seconds),
    # bounding first attempt + backoffs + failovers; a client deadline_s
    # tightens it further. 0 = unbounded.
    default_deadline_s: float = 120.0
    # Jittered-backoff envelope between failover attempts
    # (serving/retry.py:backoff_delay semantics).
    retry_base_s: float = 0.05
    retry_cap_s: float = 1.0
    # Honored Retry-After values are capped here — a replica asking for
    # a 30 s drain-budget wait must not stall a request that another
    # replica could serve right now (and a buggy/hostile header must
    # never park the router for minutes).
    retry_after_cap_s: float = 2.0

    # -- hedging -------------------------------------------------------
    # Fire a second (hedged) attempt on a different replica when the
    # first has been in flight longer than hedge_factor * observed-p99
    # latency (floored at hedge_min_s). First reply wins. 0 = off.
    hedge_factor: float = 0.0
    hedge_min_s: float = 0.25

    # -- load scoring (power-of-two-choices inputs) --------------------
    # score = queue_weight * queue_depth/slots
    #       + slot_weight  * slot_occupancy/slots
    #       + kv_weight    * kv_utilization
    #       + inflight/slots   (router-side, always on: the passive
    #         metrics are probe-stale; in-flight counts are not)
    queue_weight: float = 1.0
    slot_weight: float = 1.0
    kv_weight: float = 0.5

    # -- admission shedding / affinity ---------------------------------
    # Before shedding (or failing a mid-failover request), wait up to
    # this long for SOME replica to become eligible — it bridges the
    # sub-second windows where a rolling restart has one replica
    # draining and the other not yet re-admitted. Bounded additionally
    # by the request's deadline. 0 = shed immediately.
    wait_for_replica_s: float = 2.0
    # Retry-After sent when the router itself sheds (zero eligible
    # replicas, or every eligible replica already tried and failed).
    shed_retry_after_s: float = 1.0
    # Sticky session routing: requests carrying a "session_id" stick to
    # one replica (prefix-cache locality groundwork, ROADMAP item 1)
    # and fail over — with re-pinning — when it dies.
    affinity: bool = True
    # The affinity map is LRU-capped at this many sessions — a router
    # fronting months of unique session_ids must not grow without
    # bound. Evicting a quiet session only costs it its pin.
    affinity_max_sessions: int = 10_000

    # -- fleet metrics staleness ---------------------------------------
    # /fleet/metrics re-serves each replica's LAST probed /metrics body.
    # Bodies older than this are EXCLUDED from the aggregation (a
    # blackholed replica's hour-old counters must not be silently judged
    # as current); every replica's age is stamped as a
    # fleet_scrape_age_seconds gauge so downstream judges
    # (tools/slo_report.py --max-scrape-age, the autoscaler) can apply
    # their own bound. 0 = legacy unbounded behavior.
    metrics_max_age_s: float = 10.0

    # -- live migration / resume-by-replay (serving/migrate.py) --------
    # Total wall-clock budget for migrating ONE slot (destination probe
    # + export + checksummed transfer + import ACK). A migration that
    # cannot land within it falls back to replay — the request is never
    # harmed either way. 0 disables migration: drain degrades to the
    # replay/plain-retry rungs only.
    migrate_budget_s: float = 10.0
    # A migrated continuation can be migrated AGAIN while the router is
    # following it (one-at-a-time rolling restarts drain the destination
    # next); /migrate/await then answers another forwarding pointer.
    # The router follows the chain up to this many hops before falling
    # back to the replay rung — a bound, not a retry count, so a
    # pathological ping-pong can never loop forever.
    migrate_max_hops: int = 4
    # Per-request cap on journaled emitted tokens (ReplayJournal). A
    # runaway generation stops growing its entry; replay then degrades
    # gracefully to a longer — still bit-exact — re-decode of the tail.
    replay_journal_max_tokens: int = 4096
    # Finished-entry LRU size: journal ids of completed requests are
    # remembered this long so late duplicate replies resolve without
    # re-registering, bounded against months of unique requests.
    replay_journal_max_finished: int = 1024

    # -- predictive admission (serving/admission.py) -------------------
    # When on, the router's shed paths (no_replica, exhausted failover,
    # proactive admission sheds) compute an HONEST Retry-After from
    # fleet-wide capacity — backlog at-or-above the request's priority
    # class divided by the MEASURED fleet service rate — instead of the
    # static shed_retry_after_s. Falls back to the static value until
    # enough traffic has been observed to measure a rate.
    admission_predictive: bool = True
    # EWMA halflife for the measured fleet service rate (req/s).
    admission_rate_halflife_s: float = 10.0
    # Cap on the computed Retry-After (a deep backlog must answer "come
    # back in 30 s", not "come back in an hour" — clients treat large
    # values as outages).
    admission_max_retry_after_s: float = 30.0
    # Proactive shedding: reject a request whose PREDICTED wait
    # (backlog ahead of its class / service rate) exceeds this bound
    # scaled by its class multiplier (high 2x, normal 1x, batch 0.5x —
    # batch sheds first, high last). 0 = never shed proactively; the
    # honest Retry-After still applies to organic sheds.
    admission_wait_bound_s: float = 0.0

    def __post_init__(self):
        for name in ("probe_interval_s", "probe_timeout_s",
                     "probe_backoff_s", "probe_backoff_max_s",
                     "default_deadline_s", "retry_base_s", "retry_cap_s",
                     "retry_after_cap_s", "hedge_factor", "hedge_min_s",
                     "queue_weight", "slot_weight", "kv_weight",
                     "wait_for_replica_s", "shed_retry_after_s",
                     "metrics_max_age_s", "migrate_budget_s",
                     "admission_rate_halflife_s",
                     "admission_max_retry_after_s",
                     "admission_wait_bound_s"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}"
                )
        if self.eject_after < 1:
            raise ValueError(
                f"eject_after must be >= 1, got {self.eject_after}"
            )
        if self.readmit_after < 1:
            raise ValueError(
                f"readmit_after must be >= 1, got {self.readmit_after}"
            )
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.affinity_max_sessions < 1:
            raise ValueError(
                f"affinity_max_sessions must be >= 1, got "
                f"{self.affinity_max_sessions}"
            )
        if self.migrate_max_hops < 1:
            raise ValueError(
                f"migrate_max_hops must be >= 1, got "
                f"{self.migrate_max_hops}"
            )
        for name in ("replay_journal_max_tokens",
                     "replay_journal_max_finished"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}"
                )

    def replace(self, **kw) -> "RouterConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class AutoscalerConfig:
    """Fleet control-plane knobs (tools/autoscaler.py).

    The autoscaler closes the loop over surfaces that already exist:
    it polls the router's ``/fleet/metrics``, judges windowed SLO burn
    (obs/slo.py semantics) plus queue/KV utilization, and actuates
    replica count through tools/fleet.py's chaos-proven drain/relaunch
    machinery. Hysteresis (sustain counts), per-direction cooldowns and
    hard min/max bounds make the state machine immune to a flapping
    signal by construction — tests/test_autoscaler.py drives it with
    synthetic burn traces and the ``scale_flap`` fault.
    """

    # Seconds between /fleet/metrics polls (one control tick each).
    poll_interval_s: float = 1.0
    # Hard replica-count bounds. The autoscaler never drains the fleet
    # below min_replicas (even at zero load) and never grows it past
    # max_replicas (even at infinite burn).
    min_replicas: int = 1
    max_replicas: int = 4
    # Scale-up trigger: windowed burn rate above this (1.0 = the SLO
    # error budget is being spent exactly as provisioned) OR
    # utilization above util_high, sustained for scale_up_sustain
    # consecutive ticks.
    scale_up_burn: float = 1.0
    # Scale-down trigger: burn below this AND utilization below
    # util_low, sustained for scale_down_sustain consecutive ticks.
    # The asymmetry (down needs a longer streak) is deliberate: adding
    # capacity late sheds traffic, removing it late only costs money.
    scale_down_burn: float = 0.5
    scale_up_sustain: int = 3
    scale_down_sustain: int = 6
    # Per-direction cooldowns: after any scale action, no further
    # action in that direction until this much time has passed (the
    # fleet must re-equilibrate before the signal is trusted again).
    cooldown_up_s: float = 5.0
    cooldown_down_s: float = 15.0
    # Utilization score thresholds: the score is the max of fleet
    # queue-pressure (queued / total slots), mean KV utilization and
    # mean host-tier utilization over FRESH replicas.
    util_high: float = 0.85
    util_low: float = 0.30
    # Metrics bodies older than this (per-replica scrape_age_seconds)
    # are treated as MISSING, not current — a blackholed replica must
    # not feed the control loop hour-old numbers.
    stale_after_s: float = 5.0
    # SLO objective bounds used for the windowed burn computation
    # (same semantics as tools/slo_report.py --ttft/--itl/--target).
    ttft_threshold_s: float = 1.0
    itl_threshold_s: float = 0.25
    slo_target: float = 0.99

    # -- canaried rollout ----------------------------------------------
    # Traffic fraction the router splits to a designated canary
    # replica while its window runs.
    canary_fraction: float = 0.25
    # Canary observation window (seconds) before the judge rules.
    canary_window_s: float = 15.0
    # Judge: the canary must hold windowed burn at or under this...
    canary_max_burn: float = 1.0
    # ...and its TTFT p95 must not exceed the control replicas' pooled
    # p95 by more than this fraction (0.5 = +50%).
    canary_max_regress: float = 0.5
    # A verdict needs at least this many canary-served requests in the
    # window; fewer is "inconclusive" and the controller ROLLS BACK
    # (never promote on no evidence).
    canary_min_requests: int = 8
    # Quality axis (obs/quality.py): a canary whose
    # serving_quality_drift (PSI vs the fleet's reference fingerprint)
    # exceeds this rolls back even when latency is flat — the knee of
    # the conventional PSI reading ("> 0.25 = shifted"). 0 = quality
    # drift never gates (e.g. a fleet without quality telemetry).
    canary_max_drift: float = 0.25
    # ...and a canary whose constraint-validity rate falls more than
    # this far below the control replicas' rate rolls back too (a
    # checkpoint that stops satisfying its FSMs is broken regardless
    # of its latency). 0 = validity delta never gates.
    canary_max_validity_delta: float = 0.05

    def __post_init__(self):
        for name in ("poll_interval_s", "scale_up_burn",
                     "scale_down_burn", "cooldown_up_s",
                     "cooldown_down_s", "util_high", "util_low",
                     "stale_after_s", "ttft_threshold_s",
                     "itl_threshold_s", "canary_window_s",
                     "canary_max_burn", "canary_max_regress",
                     "canary_max_drift", "canary_max_validity_delta"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}"
                )
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if self.scale_up_sustain < 1 or self.scale_down_sustain < 1:
            raise ValueError("sustain counts must be >= 1")
        if not 0.0 < self.slo_target < 1.0:
            raise ValueError(
                f"slo_target must be in (0, 1), got {self.slo_target}"
            )
        if not 0.0 < self.canary_fraction < 1.0:
            raise ValueError(
                f"canary_fraction must be in (0, 1), got "
                f"{self.canary_fraction}"
            )
        if self.canary_min_requests < 1:
            raise ValueError(
                f"canary_min_requests must be >= 1, got "
                f"{self.canary_min_requests}"
            )

    def replace(self, **kw) -> "AutoscalerConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh. The reference has no working distributed path
    (NCCL/DDP imported but never initialized, train.py:7-10,88); this is the
    TPU-native replacement: axes map onto ICI.
    """

    pipeline: int = 1  # pipeline parallel (GPipe stages, parallel/pipeline.py)
    data: int = 1  # data parallel (batch sharding + gradient psum)
    fsdp: int = 1  # parameter/optimizer sharding over the data axis group
    tensor: int = 1  # tensor parallel (head / ffn-hidden sharding)
    sequence: int = 1  # context parallel (ring attention over sequence)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        # pipeline is the LAST (fastest-varying, stride-1) axis so
        # consecutive stages are adjacent in jax.devices() enumeration
        # order — the best default for the ppermute activation handoff
        # (true physical torus adjacency would need
        # jax.experimental.mesh_utils.create_device_mesh on big slices)
        return ("data", "fsdp", "tensor", "sequence", "pipeline")

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.data, self.fsdp, self.tensor, self.sequence, self.pipeline)

    @property
    def n_devices(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n


@dataclass(frozen=True)
class TrainConfig:
    """Training recipe, mirroring train.py:57-93 field for field."""

    model: ModelConfig = field(default_factory=ModelConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)

    # Optimization (train.py:67-78)
    grad_acc_steps: int = 1  # train.py:68
    micro_batch_size: int = 32  # train.py:69 (per optimizer step, pre-DP-split)
    max_iters: int = 40_000  # train.py:70
    eval_interval: int = 500  # train.py:71
    eval_iters: int = 200  # train.py:72
    learning_rate: float = 3.2e-4  # train.py:73
    min_lr: float = 6e-5  # train.py:74
    weight_decay: float = 0.1  # train.py:75
    beta1: float = 0.9  # train.py:76
    beta2: float = 0.95  # train.py:77
    warmup_iters: int = 1000  # train.py:78
    grad_clip: float = 1.0  # train.py:275

    # Reference quirk preserved as a flag: train.py:223-230 doubles the head
    # count when training the control model ("Double the heads since each
    # head is smaller") so control roughly param-matches diff.
    control_head_multiplier: int = 2

    # Data (train.py:82, 155, 41-46)
    dataset: str = "tinystories"  # "tinystories" | "synthetic" | path to a .txt
    # "epoch": exact epoch-permutation shuffle matching the reference's
    # DataLoader semantics (train.py:184-191), served by the native O(1)-
    # memory Feistel bijection (data/native.py). "replacement": uniform
    # with-replacement draws (statistically equivalent for stride-1
    # windows, no permutation machinery).
    sampler: str = "epoch"
    num_train_samples: int = 1_000_000
    vocab_size: int = 12000
    min_frequency: int = 2
    val_fraction: float = 0.1  # train.py:178 (90/10 split)
    tokenizer_dir: str = "tokenizer"

    # Profiling: capture a jax.profiler trace of a few steady-state steps
    # into this directory (TensorBoard/Perfetto viewable); None = off.
    profile_dir: Optional[str] = None
    # Continuous on-device profiling (obs/device_profile.py): every
    # this-many iterations, wrap ONE train step in a jax.profiler
    # capture, parse it off-loop, and publish the per-kernel step
    # decomposition + derived MFU as device_* gauges (the --metrics-port
    # sidecar), {"record":"device_profile"} rows in metrics.jsonl, and a
    # device-lane Chrome trace stitchable under the host timeline
    # (tools/trace_stitch.py). Mutually exclusive in practice with a
    # profile_dir window (the jax profiler is global; an overlapping
    # capture is counted as a failure, never fatal). 0 = off.
    profile_every: int = 0
    # Rotating spool for the sampled captures; "auto" derives
    # `<checkpoint_path stem>.profiles` so concurrent runs in one
    # directory never share a spool.
    profile_spool_dir: str = "auto"

    # Observability (obs/; no reference analog).
    # Prometheus sidecar: serve the trainer's metrics registry at
    # http://0.0.0.0:<port>/metrics from a daemon thread (obs/http.py)
    # so a scraper can watch a live run. 0 = off. Multi-process runs
    # bind it on process 0 only.
    metrics_port: int = 0
    # Host-side span trace (obs/spans.py): write Chrome-trace-event JSON
    # of the train loop (data_wait / dispatch / block spans per step;
    # open in Perfetto) to this path. Complements profile_dir, which
    # captures the DEVICE-side XLA timeline. None = off.
    trace_path: Optional[str] = None

    # Logging (train.py:90-93)
    log_interval: int = 10
    wandb_project: str = "diff-transformer"
    wandb_run_name: Optional[str] = None
    use_wandb: bool = False  # wandb sink is optional; stdout+jsonl always on
    metrics_path: Optional[str] = "metrics.jsonl"

    # Checkpointing (train.py:307-317 saved; resume is new capability)
    checkpoint_path: str = "best_model.ckpt"
    # Preemption safety: a resumable last-state checkpoint written on ANY
    # trainer exit (SIGTERM, Ctrl-C, crash, completion). "auto" derives
    # `<checkpoint_path stem>.last<ext>` so concurrent runs in one
    # directory never clobber each other's rescue checkpoint; None
    # disables; any other string is used verbatim.
    last_checkpoint_path: Optional[str] = "auto"
    resume_from: Optional[str] = None
    # Minimum seconds between best-checkpoint DISK writes. 0 = the
    # reference's write-on-every-improvement (train.py:307-317). With a
    # positive throttle the best state is still snapshotted ON DEVICE at
    # every improvement and any pending snapshot is flushed at exit
    # (after the rescue save), so the final best checkpoint is identical
    # on every exit path EXCEPT a multi-process crash: there the flush
    # (a collective) must be skipped like the rescue save, and a
    # deferred improvement is lost — best.ckpt then holds the last
    # WRITTEN best, not the last observed one. Useful where
    # device->host transfer is slow (measured 5-7 MB/s on this image's
    # tunneled chip: a recipe-scale state write costs ~3 min).
    checkpoint_min_interval_s: float = 0.0

    # Durable rotating step checkpoints (train/ckpt_writer.py). Every
    # ckpt_interval iterations the trainer snapshots the full train
    # state into `<ckpt_dir>/step-NNNNNNNN/`, certified by a per-file
    # SHA-256 manifest written last (its presence = the save completed;
    # loads re-verify digests, so corruption is never silently
    # resumed). 0 = off (best/last checkpoints still written and still
    # manifest-certified).
    ckpt_interval: int = 0
    # Root of the step-checkpoint tree. "auto" derives
    # `<checkpoint_path stem>.steps` so concurrent runs in one
    # directory never share a rotation tree.
    ckpt_dir: str = "auto"
    # Write step checkpoints from a background writer thread: the train
    # loop blocks only for the device->host snapshot; serialization,
    # file I/O, certification and retention GC run off-loop. If a save
    # is still in flight at the next interval the loop blocks until it
    # drains (back-pressure; the blocked time is the ckpt_blocked
    # histogram in obs/). False = write inline (the loop stalls for the
    # full save).
    ckpt_async: bool = True
    # Retention: keep the newest N verified step checkpoints...
    ckpt_keep_last: int = 3
    # ...plus every checkpoint whose step is a multiple of this,
    # forever (0 = none) — the cheap long-horizon audit trail.
    ckpt_keep_every: int = 0

    # Fault tolerance (train/anomaly.py; no reference analog). The
    # anomaly guard computes a per-step ``bad`` flag (non-finite
    # loss/grad-norm, or grad-norm above spike_factor x a running EMA of
    # good-step norms) INSIDE the jitted step and skips the optimizer
    # update under lax.cond — zero recompiles, zero extra collectives.
    # The trainer keeps a periodic on-device good-state snapshot, rolls
    # back to it after rollback_after consecutive bad steps, and aborts
    # with TrainingDivergedError after max_rollbacks rollbacks (the
    # finite-check rescue save then refuses to overwrite the good
    # checkpoint). Unsupported (auto-disabled) on the pipeline path.
    anomaly_guard: bool = True
    # spike when grad_norm > spike_factor * EMA(good grad norms); the
    # non-finite check is always on regardless
    anomaly_spike_factor: float = 4.0
    anomaly_ema_beta: float = 0.99
    # good steps before spike detection arms (the EMA must see real
    # norms first; early training legitimately swings)
    anomaly_warmup_steps: int = 50
    # consecutive bad steps before the trainer rolls back to the
    # snapshot (skipping already protected the state; a persistent
    # streak means the state itself is suspect)
    anomaly_rollback_after: int = 20
    # rollbacks before the run aborts cleanly
    anomaly_max_rollbacks: int = 3
    # iterations between good-state snapshots (one extra train state in
    # HBM — same footprint note as checkpoint_min_interval_s)
    anomaly_snapshot_interval: int = 200
    # iterations between host polls of the guard's bad_streak scalar.
    # Each poll blocks on the step's result, costing the async-dispatch
    # overlap for that iteration (~launch latency); 1 = react
    # immediately, the default amortizes it to noise. Skipping itself
    # happens every step on-device regardless of this cadence.
    anomaly_check_interval: int = 10

    # Overlap-scheduled data-parallel gradient sync (parallel/dp_step.py).
    # On a PURE data-parallel mesh (data > 1, every other axis 1) the
    # step runs under shard_map with the gradient all-reduce issued PER
    # LAYER-GROUP BUCKET from inside the backward pass (a custom-vjp
    # identity on each bucket's params), so the collective for layer k's
    # gradients overlaps the backward compute of layers < k instead of
    # running fully exposed after it. Numerically the same mean-gradient
    # (modulo float reduction order); single jit, donated state, zero
    # recompiles — pinned in tests/test_fused_ffn.py. Ineligible meshes
    # (fsdp/tensor/sequence/pipeline > 1) fall back to the GSPMD path
    # regardless of this flag.
    dp_overlap: bool = True
    # Consecutive transformer blocks per gradient-sync bucket. 1 = one
    # all-reduce per layer (max overlap, most collectives); n_layer =
    # one bucket (no overlap — the GSPMD schedule, minus fusion).
    # Embeddings and the ln_f/lm_head tail always form their own
    # buckets.
    dp_bucket_layers: int = 2

    # Distributed-training resilience (train/watchdog.py,
    # parallel/heartbeat.py). step_deadline_s is the trainer analogue
    # of ServingConfig.step_time_budget_s: armed around each jitted-
    # step dispatch/block (eval and checkpoint writes run disarmed); a
    # hung iteration dumps hang_report.json (all-thread stacks, last
    # device_profile row, compile counter) and exits with the distinct
    # hang code the supervisor restarts under its own budget. Both are
    # pure host-side threads: compile count is unaffected (pinned in
    # tests/test_watchdog.py). 0 = off.
    step_deadline_s: float = 0.0
    # hang_report.json destination; "auto" derives
    # `<checkpoint_path stem>.hang_report.json`.
    hang_report_path: str = "auto"
    # Multi-host liveness mesh: a shared-filesystem directory (every
    # host must see it — the checkpoint mount qualifies) where each
    # process publishes a heartbeat file every heartbeat_interval_s
    # seconds off-loop. A peer silent past heartbeat_timeout_s trips
    # the local watchdog immediately (coordinated abort) instead of
    # waiting out a wedged collective. None = off.
    heartbeat_dir: Optional[str] = None
    heartbeat_interval_s: float = 1.0
    heartbeat_timeout_s: float = 10.0
    # Elastic resume: a checkpoint may be resumed onto a DIFFERENT
    # mesh shape / global batch (checkpoints are stored host-canonical,
    # so same param shapes reshard freely; the epoch sampler fast-
    # forwards from the checkpoint's recorded consumed-window count so
    # the permutation stays exact across batch-size changes). When
    # exactness is impossible — the consumed count lands mid-way
    # through a new-size accumulation boundary, or a legacy checkpoint
    # predates the recorded count while the batch math changed — the
    # resume raises a typed ElasticResumeError unless this escape
    # hatch accepts the (bounded) inexactness.
    allow_inexact_resume: bool = False

    # Fault injection spec (utils/faults.py), merged with the DTX_FAULTS
    # env var. Testing/chaos only; None = inert.
    faults: Optional[str] = None

    def resolved_last_checkpoint_path(self) -> Optional[str]:
        if self.last_checkpoint_path != "auto":
            return self.last_checkpoint_path
        import os

        root, ext = os.path.splitext(self.checkpoint_path)
        return f"{root}.last{ext or '.ckpt'}"

    def resolved_ckpt_dir(self) -> str:
        """Root of the rotating step-checkpoint tree
        (train/ckpt_writer.py); "auto" keys it off checkpoint_path like
        the rescue checkpoint, so runs never share a rotation tree."""
        if self.ckpt_dir != "auto":
            return self.ckpt_dir
        import os

        root, _ = os.path.splitext(self.checkpoint_path)
        return f"{root}.steps"

    def resolved_hang_report_path(self) -> str:
        """Watchdog hang-report destination (train/watchdog.py);
        "auto" keys it off checkpoint_path like the rotation tree, so
        concurrent runs in one directory never clobber each other's
        post-mortem."""
        if self.hang_report_path != "auto":
            return self.hang_report_path
        import os

        root, _ = os.path.splitext(self.checkpoint_path)
        return f"{root}.hang_report.json"

    def resolved_profile_spool(self) -> str:
        """Spool dir for sampled device-profile captures
        (obs/device_profile.py); "auto" keys it off checkpoint_path
        like the rotation tree."""
        if self.profile_spool_dir != "auto":
            return self.profile_spool_dir
        import os

        root, _ = os.path.splitext(self.checkpoint_path)
        return f"{root}.profiles"

    seed: int = 1337  # train.py:329-330

    def resolved_model(self) -> ModelConfig:
        """Apply trainer-level switches to the model config: the
        control-head-doubling quirk (train.py:226) and the single source of
        truth for vocab_size (the trainer's, which the tokenizer produces —
        train.py:160)."""
        m = self.model
        if m.vocab_size != self.vocab_size:
            m = m.replace(vocab_size=self.vocab_size)
        if m.model == "control" and self.control_head_multiplier != 1:
            m = m.replace(n_head=m.n_head * self.control_head_multiplier)
        return m

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        d: dict[str, Any] = dataclasses.asdict(self)
        return d
