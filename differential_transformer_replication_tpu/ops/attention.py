"""Merged-head attention ops (XLA path).

The reference computes attention one head at a time in Python loops
(control.py:76, diff_transformer.py:89, Ndiff_transformer.py:142) — the
single biggest performance sin to fix on TPU. Here every variant is a
batched einsum over all heads at once: shapes ``(B, T, H, d)`` so the MXU
sees large contractions, with softmax in float32 (matching the numerics
the reference gets from CUDA AMP's fp32 softmax) and matmuls in the
compute dtype.

Behavioral parity:
  - scale is ``1/sqrt(head_size)`` (control.py:51, diff_transformer.py:57,
    Ndiff_transformer.py:98),
  - causal mask fills future positions with -inf BEFORE softmax
    (control.py:55),
  - attention-probability dropout is applied per map, independently
    (diff_transformer.py:66-67), before the lambda combination,
  - diff combine: ``att1 - lambda * att2`` (diff_transformer.py:70),
  - ndiff combine: ``lambda_0*att_0 + sum_i sign_i*lambda_i*att_i``
    (Ndiff_transformer.py:119-123).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.ops.dropout import dropout as _dropout


def causal_mask(seq_len: int) -> jnp.ndarray:
    """Lower-triangular keep-mask, the ``tril`` buffer of control.py:31."""
    return jnp.tril(jnp.ones((seq_len, seq_len), dtype=bool))


def masked_softmax(scores: jnp.ndarray, mask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """float32 softmax over the last axis with -inf masking
    (control.py:55-58). ``mask`` broadcasts against ``scores``; True=keep."""
    scores = scores.astype(jnp.float32)
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    return jax.nn.softmax(scores, axis=-1)


def _probs(
    q: jnp.ndarray,
    k: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    dropout_rate: float,
    rng: Optional[jax.Array],
) -> jnp.ndarray:
    """Scores -> masked fp32 softmax -> dropout. q, k: (B, T, H, d) ->
    probs (B, H, T, T)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    probs = masked_softmax(scores, mask)
    return _dropout(probs, dropout_rate, rng)


def vanilla_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """Standard causal attention, all heads at once (control.py:38-63).

    q, k, v: (B, T, H, d) -> (B, T, H, d).
    """
    probs = _probs(q, k, mask, dropout_rate, rng)
    return jnp.einsum("bhts,bshd->bthd", probs.astype(v.dtype), v)


def diff_attention(
    q1: jnp.ndarray,
    k1: jnp.ndarray,
    q2: jnp.ndarray,
    k2: jnp.ndarray,
    v: jnp.ndarray,
    lam: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """Two-term differential attention (diff_transformer.py:50-73).

    q1/k1/q2/k2: (B, T, H, d); v: (B, T, H, 2d); lam: per-head scalars (H,)
    in float32. Returns (B, T, H, 2d).
    """
    rng1 = rng2 = None
    if rng is not None:
        rng1, rng2 = jax.random.split(rng)
    att1 = _probs(q1, k1, mask, dropout_rate, rng1)
    att2 = _probs(q2, k2, mask, dropout_rate, rng2)
    # NOTE: combining on the maps (not out = att1@v - lam*(att2@v), which
    # is algebraically equal) measured FASTER — XLA fuses this subtract
    # into the value matmul, while the restructured form doubles the PV
    # matmuls (174.8k -> 170.2k tok/s at recipe scale when tried).
    diff = att1 - lam[None, :, None, None] * att2  # fp32 combine
    return jnp.einsum("bhts,bshd->bthd", diff.astype(v.dtype), v)


def ndiff_attention(
    qs: jnp.ndarray,
    ks: jnp.ndarray,
    v: jnp.ndarray,
    lams: jnp.ndarray,
    signs: jnp.ndarray,
    *,
    mask: Optional[jnp.ndarray] = None,
    dropout_rate: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """N-term alternating differential attention
    (Ndiff_transformer.py:95-126), all terms batched into a leading axis
    instead of the reference's Python term loop.

    qs/ks: (n_terms, B, T, H, d); v: (B, T, H, 2d); lams: (n_terms, H)
    float32; signs: (n_terms,) with signs[0]=+1 (the first map is scaled by
    lambda_0, Ndiff_transformer.py:119). Returns (B, T, H, 2d).
    """
    scale = 1.0 / (qs.shape[-1] ** 0.5)
    scores = jnp.einsum("nbthd,nbshd->nbhts", qs, ks) * scale
    probs = masked_softmax(scores, mask)  # (n, B, H, T, T) fp32
    probs = _dropout(probs, dropout_rate, rng)
    coeff = signs[:, None] * lams  # (n_terms, H)
    diff = jnp.einsum("nh,nbhts->bhts", coeff.astype(jnp.float32), probs)
    return jnp.einsum("bhts,bshd->bthd", diff.astype(v.dtype), v)
