"""Rotary position embeddings.

Real-arithmetic equivalent of the reference's complex-number formulation
(control.py:4-22, duplicated Ndiff_transformer.py:4-22): the reference packs
consecutive feature pairs ``(x[2i], x[2i+1])`` into complex numbers and
multiplies by ``exp(i * t * theta_j)``. Here we keep everything real (TPUs
have no complex MXU path): split even/odd lanes, rotate, re-interleave.

Parity notes:
  - frequencies: ``1 / theta**(2j/d)`` for ``j in [0, d/2)`` (control.py:6),
  - the rotation is computed in float32 and cast back to the input dtype,
    matching the reference's explicit upcast (control.py:17,22),
  - the table is truncated to the actual sequence length at apply time
    (control.py:18).
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(head_dim: int, max_seq_len: int, theta: float = 10000.0):
    """Precompute the (cos, sin) tables, each of shape ``(max_seq_len, head_dim // 2)``.

    Equivalent to the modulus/argument of ``precompute_freqs_cis``
    (control.py:4-9): ``torch.polar(ones, outer(t, freqs))`` has
    ``cos(t * f_j) + i sin(t * f_j)`` entries.
    """
    j = jnp.arange(0, head_dim, 2, dtype=jnp.float32)[: head_dim // 2]
    freqs = 1.0 / (theta ** (j / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32)
    angles = jnp.outer(t, freqs)  # (T, d/2)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    headed: bool | None = None,
) -> jnp.ndarray:
    """Rotate ``x``.

    Layout rule (when ``headed`` is None): ndim >= 4 means the merged-head
    layout ``(..., T, H, d)`` (tables broadcast over the head axis); ndim <=
    3 means ``(..., T, d)``, the reference's per-head layout
    (control.py:11-22). Pass ``headed`` explicitly for ambiguous ranks
    (an unbatched ``(T, H, d)`` is rank 3 and would otherwise be rotated by
    head index).

    ``cos``/``sin`` have shape ``(>=T, d//2)`` and are truncated to T
    (control.py:18). Pairing is over consecutive features, matching
    ``x.reshape(*, -1, 2)`` + ``view_as_complex`` (control.py:17): the even
    lane is the real part, the odd lane the imaginary part.
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    x_even = xf[..., 0::2]
    x_odd = xf[..., 1::2]

    if headed is None:
        headed = x.ndim >= 4
    if headed:
        # (..., T, H, d): broadcast tables over the head axis.
        seq_len = x.shape[-3]
        c = cos[:seq_len][:, None, :]
        s = sin[:seq_len][:, None, :]
    else:
        seq_len = x.shape[-2]
        c = cos[:seq_len]
        s = sin[:seq_len]

    rot_even = x_even * c - x_odd * s
    rot_odd = x_even * s + x_odd * c
    out = jnp.stack([rot_even, rot_odd], axis=-1).reshape(x.shape)
    return out.astype(orig_dtype)
