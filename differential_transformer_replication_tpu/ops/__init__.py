from differential_transformer_replication_tpu.ops.rope import rope_cos_sin, apply_rope
from differential_transformer_replication_tpu.ops.norms import layer_norm, group_layer_norm
from differential_transformer_replication_tpu.ops.swiglu import swiglu
from differential_transformer_replication_tpu.ops.lambdas import (
    lambda_init_schedule,
    diff_lambda,
    ndiff_lambdas,
    ndiff_signs,
)
from differential_transformer_replication_tpu.ops.attention import (
    causal_mask,
    masked_softmax,
    vanilla_attention,
    diff_attention,
    ndiff_attention,
)
from differential_transformer_replication_tpu.ops.flash import (
    flash_chunk_attention,
    flash_diff_attention,
    flash_ndiff_attention,
    flash_vanilla_attention,
    multi_stream_flash_attention,
    multi_stream_flash_attention_bh,
)
from differential_transformer_replication_tpu.ops.losses import (
    fused_linear_cross_entropy,
)
from differential_transformer_replication_tpu.ops.fused_norm_residual import (
    fused_add_group_norm,
    fused_add_norm,
    fused_group_norm,
    fused_norm,
)
from differential_transformer_replication_tpu.ops.fused_ffn import (
    fused_swiglu,
)
from differential_transformer_replication_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
    dequantize_kv,
    quantize_kv,
    quantize_params_int8,
)

__all__ = [
    "rope_cos_sin",
    "apply_rope",
    "layer_norm",
    "group_layer_norm",
    "swiglu",
    "lambda_init_schedule",
    "diff_lambda",
    "ndiff_lambdas",
    "ndiff_signs",
    "causal_mask",
    "masked_softmax",
    "vanilla_attention",
    "diff_attention",
    "ndiff_attention",
    "multi_stream_flash_attention",
    "multi_stream_flash_attention_bh",
    "flash_chunk_attention",
    "flash_vanilla_attention",
    "flash_diff_attention",
    "flash_ndiff_attention",
    "fused_linear_cross_entropy",
    "fused_add_group_norm",
    "fused_add_norm",
    "fused_group_norm",
    "fused_norm",
    "fused_swiglu",
    "decode_attention",
    "decode_attention_reference",
    "dequantize_kv",
    "quantize_kv",
    "quantize_params_int8",
]
