"""SwiGLU activation: ``silu(x @ Wg + bg) * (x @ Wx + bx)``.

Replicates the reference's SwiGLU module (control.py:80-90, copied at
diff_transformer.py:95-105 and Ndiff_transformer.py:148-158). Both linears
carry biases (the reference uses ``nn.Linear`` defaults).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def swiglu(
    x: jnp.ndarray,
    w_gate: jnp.ndarray,
    b_gate: jnp.ndarray,
    w_xform: jnp.ndarray,
    b_xform: jnp.ndarray,
) -> jnp.ndarray:
    """``x``: (..., in); weights stored (in, out) so this is ``x @ W + b``
    (the transpose of torch's (out, in) storage — same math)."""
    gate = jax.nn.silu(x @ w_gate + b_gate)
    xform = x @ w_xform + b_xform
    return gate * xform
