"""Learnable-lambda machinery for differential attention.

The reference computes lambda inside each head's forward with an in-place
buffer write (diff_transformer.py:41-48 writes ``dynamic_init`` into a
registered buffer every call, :44). Here lambda is a pure function: the
dynamic init is computed from the static 1-based ``layer_idx`` at trace
time and never stored.

Parity quirks preserved (SURVEY.md section 2.1):
  - the dynamic schedule ``0.8 - 0.6*exp(-0.3*(layer_idx - 1))`` uses
    1-BASED layer indices (diff_transformer.py:43; blocks are enumerated
    from 1 at diff_transformer.py:161 / Ndiff_transformer.py:216),
  - the multi-head OUTPUT scale is a separate, never-updated buffer fixed
    at 0.8, making the post-norm scale a constant ``1 - 0.8 = 0.2`` at
    every layer (diff_transformer.py:86,91) — see OUTPUT_SCALE,
  - N-term lambdas: term 0 is ``mean(exp(lq0*lk0) + init)`` (no
    subtraction), term i>0 subtracts term i-1's exponential
    (Ndiff_transformer.py:85-93).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

# diff_transformer.py:86,91 — the MultiHead*DiffAttention modules keep their
# own lambda_init buffer at its initial 0.8 forever, so the output scale is
# the constant (1 - 0.8), NOT a function of the dynamic per-layer schedule.
OUTPUT_SCALE = 1.0 - 0.8


def lambda_init_schedule(layer_idx):
    """Dynamic per-layer lambda_init, 1-based layer index
    (diff_transformer.py:43). Layer 1 -> 0.2, 2 -> 0.3555..., 8 -> 0.7265...

    Accepts a static Python int (computed host-side, the usual case) or a
    traced integer (the pipeline-parallel path scans over a stage's layer
    stack, so the layer index is a loop variable — parallel/pipeline.py).
    """
    if isinstance(layer_idx, (int, float)):
        return 0.8 - 0.6 * math.exp(-0.3 * (float(layer_idx) - 1.0))
    idx = jnp.asarray(layer_idx, jnp.float32)
    return 0.8 - 0.6 * jnp.exp(-0.3 * (idx - 1.0))


def diff_lambda(
    lambda_q1: jnp.ndarray,
    lambda_k1: jnp.ndarray,
    lambda_q2: jnp.ndarray,
    lambda_k2: jnp.ndarray,
    lambda_init: float,
) -> jnp.ndarray:
    """Two-term lambda (diff_transformer.py:45-48).

    Inputs are (..., head_size) — typically (H, d) with all heads merged.
    Returns the scalar-per-head lambda of shape (...,): the MEAN over the
    head_size axis of ``exp(lq1*lk1) - exp(lq2*lk2) + init``. At zero init
    this is exactly ``lambda_init``.
    """
    vec = jnp.exp(lambda_q1 * lambda_k1) - jnp.exp(lambda_q2 * lambda_k2) + lambda_init
    return jnp.mean(vec, axis=-1)


def ndiff_lambdas(
    lambda_qs: jnp.ndarray,
    lambda_ks: jnp.ndarray,
    lambda_init: float,
) -> jnp.ndarray:
    """N-term lambdas (Ndiff_transformer.py:85-93).

    ``lambda_qs``/``lambda_ks``: (n_terms, ..., head_size). Returns
    (n_terms, ...): term 0 is ``mean(exp(lq0*lk0) + init)``; term i>0 is
    ``mean(exp(lqi*lki) - exp(lq(i-1)*lk(i-1)) + init)``.
    """
    e = jnp.exp(lambda_qs * lambda_ks)  # (n_terms, ..., d)
    prev = jnp.concatenate([jnp.zeros_like(e[:1]), e[:-1]], axis=0)
    return jnp.mean(e - prev + lambda_init, axis=-1)


def effective_diff_lambda(attn_params: dict, layer_idx: int) -> jnp.ndarray:
    """Scalar effective lambda of one diff-attention layer: the mean
    over heads of :func:`diff_lambda` — the quantity the paper's
    lambda-evolution figure tracks per layer (Ye et al., 2024, Fig. 8:
    lambda starts at the init schedule and drifts as the lambda_q/k
    vectors learn). ``layer_idx`` is 1-based, like the schedule."""
    lam = diff_lambda(
        attn_params["lambda_q"][0], attn_params["lambda_k"][0],
        attn_params["lambda_q"][1], attn_params["lambda_k"][1],
        lambda_init_schedule(layer_idx),
    )  # (H,)
    return jnp.mean(lam)


def effective_ndiff_lambdas(attn_params: dict, layer_idx: int) -> jnp.ndarray:
    """(n_terms,) effective lambdas of one ndiff layer: the mean over
    heads of :func:`ndiff_lambdas` per term (term 0 has no subtraction;
    see module docstring quirks)."""
    lams = ndiff_lambdas(
        attn_params["lambda_q"], attn_params["lambda_k"],
        lambda_init_schedule(layer_idx),
    )  # (n_terms, H)
    return jnp.mean(lams, axis=-1)


def ndiff_signs(n_terms: int) -> jnp.ndarray:
    """Alternating combination signs (Ndiff_transformer.py:119-123): the
    first map enters with ``+lambda_0`` (NOT coefficient 1 — this is why
    n_terms=2 is not numerically identical to the 2-term DiffHead), then
    ``-1 if i odd else +1`` for i >= 1."""
    signs = [1.0] + [(-1.0 if i % 2 else 1.0) for i in range(1, n_terms)]
    return jnp.asarray(signs, dtype=jnp.float32)
