"""Normalization layers.

Both the blocks' pre-LN (``nn.LayerNorm``, control.py:105-106) and the
differential attention's ``GroupLayerNorm`` (diff_transformer.py:5-20,
Ndiff_transformer.py:24-38) reduce over the ENTIRE last dimension with
biased variance and ``eps`` inside the square root.

Parity note (SURVEY.md section 2.1): despite its name and docstring, the
reference's GroupLayerNorm is NOT a per-head group norm — it computes
mean/var over the full concatenated ``num_heads * 2*head_size`` dimension
(diff_transformer.py:17-18). We replicate that behavior, not the docstring.
"""

from __future__ import annotations

import jax.numpy as jnp


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """LayerNorm over the last axis: biased variance, ``(var + eps).sqrt()``
    denominator — the exact formula at diff_transformer.py:17-19, which is
    also what ``nn.LayerNorm`` computes."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) / jnp.sqrt(var + eps)
    return (normed * weight + bias).astype(x.dtype)


def group_layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """The reference's GroupLayerNorm: a full-width LayerNorm over the
    concatenated head outputs (diff_transformer.py:15-20). Kept as a named
    alias so call sites document which reference module they replicate."""
    return layer_norm(x, weight, bias, eps=eps)
