"""Fused multi-stream flash attention — the Pallas TPU kernel.

The reference materializes full ``(T, T)`` attention maps per head and per
softmax stream (diff_transformer.py:57-70, control.py:52-62,
Ndiff_transformer.py:102-123). On TPU the O(T^2) memory traffic, not the
FLOPs, is the bottleneck, so this module computes the same math as an
online-softmax (flash) kernel that never materializes a T x T map.

One kernel serves all three model families, because each one's attention is
a *linear combination of softmax streams over a shared V*:

    out = sum_s coeff[s, h] * causal_softmax(Q_s K_s^T / sqrt(d)) @ V

  - control (control.py:52-62):            S=1, coeff = [1]
  - diff    (diff_transformer.py:70):      S=2, coeff = [1, -lambda_h]
  - ndiff   (Ndiff_transformer.py:119-123): S=n, coeff = sign_s * lambda_{s,h}

The kernel runs S online-softmax accumulators in one pass sharing the V
tiles (SURVEY.md section 7.7: "exploit linearity"), with the per-stream
coefficients applied at combine time. Scores, softmax and accumulation are
float32; tile matmuls feed the MXU in the input dtype.

Backward is a custom VJP with two Pallas kernels (dq; dk/dv) that recompute
probabilities from the saved per-stream log-sum-exp — the standard flash
backward, generalized to S streams. The per-stream outputs O_s are saved
from the forward so that d(coeff) and the flash "delta" rowsum need no
extra recompute pass.

Attention-probability dropout (diff_transformer.py:58-67) is fused
in-kernel: counter-based hash masks of the global coordinates, identical
across forward/backward and across tilings — see the dropout section
below and tests/test_flash_dropout.py.

Two kernel generations, dispatched on T (measured on v5e at the
flagship diff shapes):
  - full-K/V-resident (T <= _KV_TILE_THRESHOLD = 4096): each grid step
    holds the whole per-(b,h) K/V in VMEM; fastest at short T, stops
    compiling for training at T=5120.
  - KV-tiled (T > 4096): K/V stream through a third grid dimension with
    scratch accumulators, so VMEM holds O(block) state regardless of T.
    Verified training on one chip at T=8192 (10.7x the dense XLA path)
    and T=16384.
Sequence parallelism composes on top — parallel/ring.py shards T across
the mesh and with impl="pallas" runs the chunk kernel per ring step, so
each device only ever sees T/num_shards.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from differential_transformer_replication_tpu.utils.compat import (
    CompilerParams as _CompilerParams,
)

from differential_transformer_replication_tpu.ops.streams import (
    NEG_INF,
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)


def auto_interpret() -> bool:
    """Compiled Mosaic on TPU; interpreter everywhere else (CPU CI)."""
    return jax.default_backend() != "tpu"


_auto_interpret = auto_interpret  # internal callers


def use_flash(impl: str, dropout_rate: float, rng) -> bool:
    """Single dispatch predicate shared by all three model families.

    Attention-prob dropout is fused in-kernel (counter-based masks; see
    multi_stream_flash_attention), so the pallas path now applies
    regardless of the dropout setting. The signature keeps the
    (rate, rng) arguments so call sites document what the predicate once
    depended on — both are inert here.
    """
    del dropout_rate, rng
    return impl == "pallas"


def pick_block(desired: int, total: int) -> int:
    """Largest divisor of ``total`` that is <= desired (block shapes must
    tile the sequence exactly)."""
    b = min(desired, total)
    while total % b:
        b -= 1
    return b


_pick_block = pick_block  # internal callers


# Tile defaults by TPU generation, measured via tools/flash_sweep.py on
# v5e (see multi_stream_flash_attention's docstring). VMEM budgets differ
# across generations, so unknown kinds get conservative 256-tiles that
# compile everywhere rather than the widest measured winner.
# (blocks are (block_q, block_k, block_q_train, block_k_train))
_TUNED_BLOCKS = {
    # with bf16 MXU operands the 1024-wide K train tile fits VMEM in the
    # bare-op sweeps (tools/flash_sweep.py: +5% at T=512, +24-29% at
    # T=2048-8192 over 512-square) — but see the T-dependent cap in
    # multi_stream_flash_attention: the resident bwd kernels can't afford
    # it at 1024 < T <= _KV_TILE_THRESHOLD under the full model
    "v5 lite": (512, 1024, 512, 1024),
    "v5e": (512, 1024, 512, 1024),
}
_CONSERVATIVE_BLOCKS = (256, 512, 256, 256)


def default_blocks() -> tuple:
    """(block_q, block_k, block_q_train, block_k_train) for the current
    backend: tuned tiles on known TPU kinds, conservative ones elsewhere,
    tuned for the interpreter (tile size is semantics-free there)."""
    if jax.default_backend() != "tpu":
        return _TUNED_BLOCKS["v5 lite"]
    kind = jax.devices()[0].device_kind.lower()
    for key, blocks in _TUNED_BLOCKS.items():
        if key in kind:
            return blocks
    return _CONSERVATIVE_BLOCKS


# ---------------------------------------------------------------------------
# In-kernel attention-probability dropout (diff_transformer.py:58-67: each
# softmax map is dropped out independently, before the lambda combine).
#
# The randomness is a counter-based hash of the GLOBAL (row, col) position,
# the (b*H + h) grid index, the stream index, and a per-call seed — pure
# uint32 arithmetic, so the same code runs compiled on TPU and in the
# Pallas interpreter, and a plain-jnp twin (dropout_keep_reference) can
# reproduce the kernel's masks bit-exactly for parity tests. Because the
# mask is a function of global coordinates only, the forward and both
# backward kernels regenerate identical masks regardless of their tilings.
# The seed rides an SMEM (1, 2) float32 holding two exact 24-bit integers
# (no float<->int bitcasting needed in-kernel); the two words enter the
# hash at different rounds (dropout_keep_ids), so cross-call mask-field
# collisions need both words to match (~2^-48 per pair) and distinct
# (layer, step) calls don't birthday-collide over a full 40k-step training
# run the way a single 24-bit word would (~6k draws).
# ---------------------------------------------------------------------------


def _fmix32(x: jnp.ndarray) -> jnp.ndarray:
    """32-bit finalizer (triple32-style avalanche); wraps mod 2^32."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def dropout_keep_ids(seed_w0, seed_w1, bh, s_idx: int, row_ids, col_ids,
                     rate: float):
    """Bernoulli(1 - rate) keep mask for global attention positions.

    seed_w0/seed_w1: uint32 scalars (the two 24-bit seed words); bh:
    traced int scalar (b*H + h); s_idx: static stream index;
    row_ids/col_ids: int32 (bq, bk) global q/k positions. Returns bool
    (bq, bk). The two seed words enter at DIFFERENT rounds of the hash
    (w0 in the inner key, w1 xor'd between the finalizer rounds), so two
    calls regenerate the same mask field only if both 24-bit words
    collide jointly — ~2^-48 per pair, not the ~2^-32 a single folded
    key would give."""
    threshold = jnp.uint32(min(int(round(rate * (2.0**32))), 2**32 - 1))
    key = _fmix32(
        seed_w0
        ^ (bh.astype(jnp.uint32) * jnp.uint32(0x9E3779B1))
        ^ jnp.uint32(s_idx * 0x27D4EB2F)
    )
    x = (
        row_ids.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
        ^ col_ids.astype(jnp.uint32) * jnp.uint32(0xC2B2AE3D)
    )
    return _fmix32(_fmix32(x + key) ^ (seed_w1 * jnp.uint32(0x9E3779B1))) >= threshold


def _read_seed_words(seed_ref):
    """The seed's two exact-24-bit float32 words as uint32 scalars. Works
    on the SMEM ref in-kernel and on the (1, 2) array in the jnp twin —
    both index as [0, i]."""
    w0 = seed_ref[0, 0].astype(jnp.int32).astype(jnp.uint32)
    w1 = seed_ref[0, 1].astype(jnp.int32).astype(jnp.uint32)
    return w0, w1


def _keep_mask_block(seed_ref, bh, S: int, q_start, k_start, bq: int, bk: int,
                     rate: float, off=None):
    """(S, bq, bk) keep mask for one score block (kernel-side).

    ``off`` is the ring-chunk causal offset: subtracting it from the
    column coordinate recovers a per-device-unique K position
    (``k_local - off = k_global - my*Tl``), so on the sequence-parallel
    ring every (q, k) pair hashes distinctly across the rotation steps
    while the aligned paths (off=0) keep plain global coordinates —
    which is also what dropout_keep_reference reproduces."""
    # f32 -> i32 -> u32: Mosaic has no direct f32->u32 cast; each seed word
    # is a 24-bit integer so the value survives exactly
    w0, w1 = _read_seed_words(seed_ref)
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if off is not None:
        cols = cols - off
    return jnp.stack(
        [dropout_keep_ids(w0, w1, bh, s, rows, cols, rate) for s in range(S)]
    )


def _apply_keep(p, keep, rate: float):
    """Inverted dropout on (already-softmaxed or unnormalized) probs."""
    return jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)


def _scale_streams(x, c_ref, bh_id, S: int):
    """(bq, bk) -> (S, bq, bk): scale one block by each stream's scalar
    combine coefficient (SMEM (BH, S) table), statically unrolled —
    Mosaic rejects the equivalent (S, 1, 1)-broadcast formulation
    ("unsupported shape cast") for S >= 2. The FACTORED backward's
    per-stream dP expansion; see _bwd_dq_kernel."""
    return jnp.stack([x * c_ref[bh_id, s] for s in range(S)])


def _combine_streams(p, c_ref, bh_id, S: int):
    """(S, bq, bk) -> (bq, bk): sum of streams weighted by their scalar
    combine coefficients (statically unrolled, see _scale_streams)."""
    acc = p[0] * c_ref[bh_id, 0]
    for s in range(1, S):
        acc = acc + p[s] * c_ref[bh_id, s]
    return acc


def dropout_seed_from_rng(rng) -> jnp.ndarray:
    """(1, 2) float32 carrying two 24-bit seed words (48 bits total) drawn
    from a jax PRNG key — each exactly representable in float32, so SMEM
    can carry them without bitcasting."""
    bits = jax.random.bits(rng, (1, 2), jnp.uint32) >> 8
    return bits.astype(jnp.float32)


def dropout_keep_reference(seed: jnp.ndarray, BH: int, S: int, T: int,
                           rate: float) -> jnp.ndarray:
    """Plain-jnp twin of the kernels' mask generation: (BH, S, T, T) keep
    booleans, bit-exact with what the compiled/interpreted kernels use for
    the same ``seed`` (a (1, 2) float32 from :func:`dropout_seed_from_rng`).
    Test/oracle use only — it materializes full T x T masks."""
    w0, w1 = _read_seed_words(seed)
    rows = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    out = []
    for bh in range(BH):
        bh_t = jnp.asarray(bh, jnp.int32)
        out.append(
            jnp.stack(
                [
                    dropout_keep_ids(w0, w1, bh_t, s, rows, cols, rate)
                    for s in range(S)
                ]
            )
        )
    return jnp.stack(out)


# ---------------------------------------------------------------------------
# Shared kernel math
# ---------------------------------------------------------------------------


_BIAS_MAX_T = 1024  # resident kernels switch to the additive-mask fast
# path at T <= this: the (T, T) fp32 bias tile costs VMEM stripes of
# (block, T) per program, fine at 1024 (2 MB) but a VMEM hazard toward
# the 4096 resident limit


def causal_bias(T: int, off) -> jnp.ndarray:
    """(T, T) fp32 ADDITIVE causal mask: 0 where column c is visible to
    row r (``c <= r + off``), NEG_INF elsewhere. Built ONCE per kernel
    call outside the grid (XLA CSEs the identical subgraph across
    layers) and added onto the scores inside — one VPU pass per tile
    instead of the two iotas + compare + select the in-kernel mask
    generation costs per PROGRAM (measured ~2-3 ms/step at the recipe
    scale across the three resident kernels). Adding the finite
    NEG_INF sentinel reproduces the select exactly: a finite score
    plus -1e30 rounds to -1e30 in fp32."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    off_i = jnp.asarray(off, jnp.int32).reshape(())
    return jnp.where(cols <= rows + off_i, 0.0, NEG_INF).astype(jnp.float32)


def _scores_plus_bias(q_blk, k_blk, bias_blk, scale):
    """Score block with the precomputed additive causal mask — the
    bias-mode twin of :func:`_masked_scores` (same MXU contraction,
    dtype rules, and masking semantics; see :func:`causal_bias`)."""
    s = jax.lax.dot_general(
        q_blk, k_blk,
        dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale
    return s + bias_blk[None]


def _masked_scores(q_blk, k_blk, q_start, k_start, off, scale):
    """The score/mask block every kernel shares: ``(S, bq, bk)`` fp32
    scores ``Q K^T * scale`` with offset-causal masking (column c visible
    to row r iff ``k_start + c <= q_start + r + off``), plus the boolean
    keep-mask. q_blk/k_blk: (S, bq|bk, d) in the STORED dtype — on bf16
    inputs the MXU runs the native bf16 x bf16 -> fp32 contraction
    (preferred_element_type), which is what the XLA attention path and
    the reference's fp16-AMP matmuls (train.py:263) do; upcasting
    operands to fp32 first would run the MXU at a fraction of peak."""
    bq, bk = q_blk.shape[1], k_blk.shape[1]
    s = jax.lax.dot_general(
        q_blk, k_blk,
        dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale
    row_ids = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    col_ids = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    keep = (col_ids <= row_ids + off)[None, :, :]
    return jnp.where(keep, s, NEG_INF), keep


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref,  # (1, S, block_q, d)
    k_ref,  # (1, S, T, d)
    v_ref,  # (1, T, dv)
    off_ref,  # (1, 1) float32 SMEM: causal row offset (0 = aligned causal;
    #           +-k*Tl for ring chunks whose K lives k shards away)
    seed_ref,  # (1, 2) float32 SMEM: dropout seed (unread when rate == 0)
    *refs,  # [c_ref (BH, S) SMEM if emit_combined] then the outputs:
    #         [out_ref (1, block_q, dv) if emit_combined]
    #         [oall_ref (1, S, block_q, dv), lse_ref (1, S, block_q)
    #          if save_residuals]
    block_k: int,
    save_residuals: bool,
    emit_combined: bool = True,
    dropout_rate: float = 0.0,
    use_bias: bool = False,
):
    """One online-softmax body for all three forward modes: the combined
    primal (coeff-weighted sum of streams), the residual-saving VJP
    forward, and the per-stream ring chunk (no combine; offset-causal).
    ``use_bias`` swaps the in-kernel iota mask for the precomputed
    additive bias stripe (:func:`causal_bias`), delivered as an extra
    (block_q, T) input right before the outputs in ``refs``."""
    if use_bias:
        bias_ref, *refs = refs
    else:
        bias_ref = None
    if emit_combined:
        c_ref, *outs = refs
    else:
        c_ref, outs = None, list(refs)

    S, block_q, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    T = k_ref.shape[2]
    dv = v_ref.shape[2]
    nk = T // block_k
    bh_id = pl.program_id(0)  # read at top level: the interpreter cannot
    i = pl.program_id(1)      # lower program_id inside cond/when bodies
    q_start = i * block_q
    off = off_ref[0, 0].astype(jnp.int32)

    q = q_ref[0]  # (S, block_q, d) stored dtype — MXU-native
    scale = 1.0 / math.sqrt(d)

    def body(j, carry):
        m, l, acc = carry

        def compute(carry):
            m, l, acc = carry
            k_j = k_ref[0, :, pl.ds(j * block_k, block_k), :]
            v_j = v_ref[0, pl.ds(j * block_k, block_k), :]
            if use_bias:
                s = _scores_plus_bias(
                    q, k_j, bias_ref[:, pl.ds(j * block_k, block_k)], scale
                )
            else:
                s, _ = _masked_scores(q, k_j, q_start, j * block_k, off, scale)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))  # (S, block_q)
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[:, :, None])
            # the normalizer accumulates the UNdropped p: softmax first,
            # then dropout on the normalized map (diff_transformer.py:58-67)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            p_pv = p
            if dropout_rate > 0.0:
                keep = _keep_mask_block(
                    seed_ref, bh_id, S, q_start, j * block_k,
                    block_q, block_k, dropout_rate, off,
                )
                p_pv = _apply_keep(p, keep, dropout_rate)
            pv = jax.lax.dot_general(
                p_pv.astype(v_j.dtype), v_j,
                dimension_numbers=(((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (S, block_q, dv) fp32 accum
            acc_new = acc * alpha[:, :, None] + pv
            return m_new, l_new, acc_new

        # causal skip: K block j is entirely in the future of this Q block
        return jax.lax.cond(
            j * block_k <= q_start + block_q - 1 + off, compute, lambda c: c,
            carry,
        )

    m0 = jnp.full((S, block_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((S, block_q), jnp.float32)
    a0 = jnp.zeros((S, block_q, dv), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, a0))

    # aligned-causal rows always see the diagonal (l >= 1); ring chunks can
    # have fully masked rows, where l_safe keeps o finite and lse lands at
    # ~NEG_INF so the chunk gets zero weight in the logsumexp merge
    l_safe = jnp.maximum(l, 1e-30)
    o_s = acc / l_safe[:, :, None]  # (S, block_q, dv)
    if emit_combined:
        # combine streams with the per-(b,h) scalar coefficients (SMEM)
        bh = pl.program_id(0)
        out_ref = outs[0]
        combined = c_ref[bh, 0] * o_s[0]
        for s in range(1, S):
            combined += c_ref[bh, s] * o_s[s]
        out_ref[0] = combined.astype(out_ref.dtype)
        outs = outs[1:]
    if save_residuals:
        oall_ref, lse_ref = outs
        oall_ref[0] = o_s.astype(oall_ref.dtype)
        lse_ref[0] = (m + jnp.log(l_safe)).astype(lse_ref.dtype)


def _fwd_call(
    q: jnp.ndarray,  # (BH, S, T, d)
    k: jnp.ndarray,  # (BH, S, T, d)
    v: jnp.ndarray,  # (BH, T, dv)
    coeffs: jnp.ndarray,  # (BH, S) float32
    *,
    block_q: int,
    block_k: int,
    save_residuals: bool,
    interpret: bool,
    dropout_seed: Optional[jnp.ndarray] = None,  # (1, 2) float32
    dropout_rate: float = 0.0,
):
    BH, S, T, d = q.shape
    dv = v.shape[-1]
    nq = T // block_q
    seed = (
        dropout_seed
        if dropout_seed is not None
        else jnp.zeros((1, 2), jnp.float32)
    )
    if T > _KV_TILE_THRESHOLD:
        # stream K/V through the grid past the full-residency envelope
        results = _tiled_fwd_call(
            q, k, v, jnp.zeros((1, 1), jnp.float32), coeffs,
            block_q=block_q, block_k=block_k,
            save_residuals=save_residuals, emit_combined=True,
            interpret=interpret,
            dropout_seed=seed, dropout_rate=dropout_rate,
        )
        if save_residuals:
            return results
        return results[0], None, None
    use_bias = T <= _BIAS_MAX_T
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, save_residuals=save_residuals,
        emit_combined=True, dropout_rate=dropout_rate, use_bias=use_bias,
    )
    out_shapes = [jax.ShapeDtypeStruct((BH, T, dv), q.dtype)]
    out_specs = [
        pl.BlockSpec(
            (1, block_q, dv), lambda b, i: (b, i, 0), memory_space=pltpu.VMEM
        ),
    ]
    if save_residuals:
        # residual buffers exist only on the VJP path; the inference primal
        # must not allocate (BH, S, T, dv) of dead HBM
        out_shapes += [
            jax.ShapeDtypeStruct((BH, S, T, dv), q.dtype),
            jax.ShapeDtypeStruct((BH, S, T), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec(
                (1, S, block_q, dv), lambda b, i: (b, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, S, block_q), lambda b, i: (b, 0, i), memory_space=pltpu.VMEM
            ),
        ]
    in_specs = [
        pl.BlockSpec(
            (1, S, block_q, d), lambda b, i: (b, 0, i, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec(
            (1, S, T, d), lambda b, i: (b, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        pl.BlockSpec((1, T, dv), lambda b, i: (b, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1), lambda b, i: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM),
    ]
    inputs = [q, k, v, jnp.zeros((1, 1), jnp.float32), seed]
    if use_bias:
        in_specs.append(
            pl.BlockSpec((block_q, T), lambda b, i: (i, 0),
                         memory_space=pltpu.VMEM)
        )
        inputs.append(causal_bias(T, 0))
    # the whole (BH, S) scalar coefficient table rides in SMEM; a
    # per-bh block would violate Mosaic's (8, 128) tiling check
    in_specs.append(
        pl.BlockSpec((BH, S), lambda b, i: (0, 0), memory_space=pltpu.SMEM)
    )
    inputs.append(coeffs)
    results = pl.pallas_call(
        kernel,
        grid=(BH, nq),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(*inputs)
    if save_residuals:
        return results
    return results[0], None, None


# ---------------------------------------------------------------------------
# KV-tiled variants: K/V stream through a third grid dimension with scratch
# accumulators, so VMEM holds only O(block) state regardless of T. Selected
# automatically past the full-K/V envelope (see _KV_TILE_THRESHOLD).
# ---------------------------------------------------------------------------

# measured on v5e: the full-K/V-resident kernels stop compiling for
# training at T=5120 (flagship shapes); stream K/V above this
_KV_TILE_THRESHOLD = 4096

# The BACKWARD can switch to the KV-tiled kernels earlier than the
# forward: the resident bwd kernels are the reason the train K tile is
# clamped to 512 at 1024 < T <= _KV_TILE_THRESHOLD (see the clamp in
# multi_stream_flash_attention_bh), while the tiled bwd holds only
# O(block) state and keeps the 1024-wide tile that measured +24-29% in
# bare-op sweeps. Kept equal to _KV_TILE_THRESHOLD by default. Lowering
# this knob to a value V routes the region V < T <= _KV_TILE_THRESHOLD
# backward through the tiled kernels (the dispatch is `T > threshold`,
# so e.g. V=1024 moves T=2048/4096 off the resident backward; T <= V
# stays resident and clamped).
_BWD_KV_TILE_THRESHOLD = _KV_TILE_THRESHOLD


def _tiled_fwd_kernel(
    q_ref,  # (1, S, block_q, d)    constant over the k grid dim
    k_ref,  # (1, S, block_k, d)    streamed
    v_ref,  # (1, block_k, dv)      streamed
    off_ref,  # (1, 1) float32 SMEM
    seed_ref,  # (1, 2) float32 SMEM: dropout seed (unread when rate == 0)
    *refs,  # [c_ref if emit_combined] outputs [out][oall, lse] then
    #         scratch: m (S, block_q), l (S, block_q), acc (S, block_q, dv)
    save_residuals: bool,
    emit_combined: bool,
    dropout_rate: float = 0.0,
):
    if emit_combined:
        c_ref, *rest = refs
    else:
        c_ref, rest = None, list(refs)
    m_scr, l_scr, acc_scr = rest[-3:]
    outs = rest[:-3]

    S, block_q, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    block_k = k_ref.shape[2]
    bh = pl.program_id(0)  # read outside pl.when: the interpreter cannot
    j = pl.program_id(2)   # lower program_id from inside a when-body
    nk = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q
    off = off_ref[0, 0].astype(jnp.int32)
    scale = 1.0 / math.sqrt(d)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j * block_k <= q_start + block_q - 1 + off)
    def _():
        q = q_ref[0]
        k_j = k_ref[0]
        v_j = v_ref[0]
        s, _ = _masked_scores(q, k_j, q_start, j * block_k, off, scale)
        m = m_scr[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, :, None])
        # normalizer accumulates the UNdropped p (softmax then dropout)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1)
        p_pv = p
        if dropout_rate > 0.0:
            keep = _keep_mask_block(
                seed_ref, bh, S, q_start, j * block_k,
                block_q, block_k, dropout_rate, off,
            )
            p_pv = _apply_keep(p, keep, dropout_rate)
        pv = jax.lax.dot_general(
            p_pv.astype(v_j.dtype), v_j,
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scr[:] = acc_scr[:] * alpha[:, :, None] + pv
        m_scr[:] = m_new

    @pl.when(j == nk - 1)
    def _():
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_s = acc_scr[:] / l_safe[:, :, None]
        rest_outs = list(outs)
        if emit_combined:
            out_ref = rest_outs[0]
            combined = c_ref[bh, 0] * o_s[0]
            for s_i in range(1, S):
                combined += c_ref[bh, s_i] * o_s[s_i]
            out_ref[0] = combined.astype(out_ref.dtype)
            rest_outs = rest_outs[1:]
        if save_residuals:
            oall_ref, lse_ref = rest_outs
            oall_ref[0] = o_s.astype(oall_ref.dtype)
            lse_ref[0] = (m_scr[:] + jnp.log(l_safe)).astype(lse_ref.dtype)


def _tiled_fwd_call(
    q, k, v, offset, coeffs, *,
    block_q, block_k, save_residuals, emit_combined, interpret,
    dropout_seed=None, dropout_rate: float = 0.0,
):
    BH, S, T, d = q.shape
    dv = v.shape[-1]
    nq, nk = T // block_q, T // block_k
    seed = (
        dropout_seed
        if dropout_seed is not None
        else jnp.zeros((1, 2), jnp.float32)
    )
    in_specs = [
        pl.BlockSpec((1, S, block_q, d), lambda b, i, j: (b, 0, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, S, block_k, d), lambda b, i, j: (b, 0, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, dv), lambda b, i, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1), lambda b, i, j: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 2), lambda b, i, j: (0, 0), memory_space=pltpu.SMEM),
    ]
    inputs = [q, k, v, offset, seed]
    if emit_combined:
        in_specs.append(
            pl.BlockSpec((BH, S), lambda b, i, j: (0, 0),
                         memory_space=pltpu.SMEM)
        )
        inputs.append(coeffs)
    out_shapes, out_specs = [], []
    if emit_combined:
        out_shapes.append(jax.ShapeDtypeStruct((BH, T, dv), q.dtype))
        out_specs.append(
            pl.BlockSpec((1, block_q, dv), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
        )
    if save_residuals:
        out_shapes += [
            jax.ShapeDtypeStruct((BH, S, T, dv), q.dtype),
            jax.ShapeDtypeStruct((BH, S, T), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec((1, S, block_q, dv), lambda b, i, j: (b, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ]
    results = pl.pallas_call(
        functools.partial(
            _tiled_fwd_kernel, save_residuals=save_residuals,
            emit_combined=emit_combined, dropout_rate=dropout_rate,
        ),
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((S, block_q), jnp.float32),
            pltpu.VMEM((S, block_q), jnp.float32),
            pltpu.VMEM((S, block_q, dv), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*inputs)
    return results


def _tiled_dq_kernel(
    q_ref,  # (1, S, block_q, d)
    k_ref,  # (1, S, block_k, d)  streamed
    v_ref,  # (1, block_k, dv)    streamed
    do_ref,  # (1, block_q, dv) factored shared g | (1, S, block_q, dv)
    #          legacy (see _bwd_dq_kernel)
    lse_ref,  # (1, S, block_q)
    delta_ref,  # (1, S, block_q)
    off_ref,  # (1, 1) SMEM
    seed_ref,  # (1, 2) SMEM dropout seed
    c_ref,  # (BH, S) float32 SMEM combine coeffs (read only when factored)
    dq_ref,  # (1, S, block_q, d)
    dq_scr,  # (S, block_q, d) f32 scratch
    *,
    dropout_rate: float = 0.0,
    factored: bool = False,
):
    S, block_q, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    block_k = k_ref.shape[2]
    bh_id = pl.program_id(0)  # top-level read (see _tiled_fwd_kernel note)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = pl.program_id(1) * block_q
    off = off_ref[0, 0].astype(jnp.int32)
    scale = 1.0 / math.sqrt(d)

    @pl.when(j == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(j * block_k <= q_start + block_q - 1 + off)
    def _():
        q = q_ref[0]
        k_j = k_ref[0]
        v_j = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s, keep = _masked_scores(q, k_j, q_start, j * block_k, off, scale)
        p = jnp.where(keep, jnp.exp(s - lse[:, :, None]), 0.0)
        if factored:
            dp = _scale_streams(
                jax.lax.dot_general(
                    do, v_j,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ),
                c_ref, bh_id, S,
            )  # one matmul, per-stream scalar scale
        else:
            dp = jax.lax.dot_general(
                do, v_j,
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        if dropout_rate > 0.0:
            # dP arrives through the dropout: dP~ = mask/keep * (dO V^T)
            dkeep = _keep_mask_block(
                seed_ref, bh_id, S, q_start, j * block_k,
                block_q, block_k, dropout_rate, off,
            )
            dp = _apply_keep(dp, dkeep, dropout_rate)
        ds = p * (dp - delta[:, :, None])
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k_j.dtype), k_j,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _tiled_dkv_kernel(
    q_ref,  # (1, S, block_q, d)  streamed (innermost grid dim)
    k_ref,  # (1, S, block_k, d)
    v_ref,  # (1, block_k, dv)
    do_ref,  # (1, block_q, dv) factored shared g | (1, S, block_q, dv)
    #          legacy — streamed either way (see _bwd_dq_kernel)
    lse_ref,  # (1, S, block_q)    streamed
    delta_ref,  # (1, S, block_q)  streamed
    off_ref,  # (1, 1) SMEM
    seed_ref,  # (1, 2) SMEM dropout seed
    c_ref,  # (BH, S) float32 SMEM combine coeffs (read only when factored)
    dk_ref,  # (1, S, block_k, d)
    dv_ref,  # (1, block_k, dv)
    dk_scr,  # (S, block_k, d) f32
    dv_scr,  # (block_k, dv) f32
    *,
    dropout_rate: float = 0.0,
    factored: bool = False,
):
    S, block_k, d = k_ref.shape[1], k_ref.shape[2], k_ref.shape[3]
    block_q = q_ref.shape[2]
    bh_id = pl.program_id(0)  # top-level read (see _tiled_fwd_kernel note)
    i = pl.program_id(2)
    nq = pl.num_programs(2)
    k_start = pl.program_id(1) * block_k
    off = off_ref[0, 0].astype(jnp.int32)
    scale = 1.0 / math.sqrt(d)

    @pl.when(i == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(i * block_q + block_q - 1 + off >= k_start)
    def _():
        q_i = q_ref[0]
        k = k_ref[0]
        lse_i = lse_ref[0]
        delta_i = delta_ref[0]
        s, keep = _masked_scores(q_i, k, i * block_q, k_start, off, scale)
        p = jnp.where(keep, jnp.exp(s - lse_i[:, :, None]), 0.0)
        p_v = p
        dkeep = None
        if dropout_rate > 0.0:
            dkeep = _keep_mask_block(
                seed_ref, bh_id, S, i * block_q, k_start,
                block_q, block_k, dropout_rate, off,
            )
            p_v = _apply_keep(p, dkeep, dropout_rate)  # dropped map P~
        if factored:
            g_i = do_ref[0]  # (block_q, dv)
            # dV = (sum_s c_s P~_s)^T g: VPU combine, one matmul
            p_c = _combine_streams(p_v, c_ref, bh_id, S).astype(g_i.dtype)
            dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
                p_c, g_i,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            dp = _scale_streams(
                jax.lax.dot_general(
                    g_i, v_ref[0],
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ),
                c_ref, bh_id, S,
            )
        else:
            do_i = do_ref[0]
            p_lo = p_v.astype(do_i.dtype)
            dv_acc = dv_scr[:]
            for s_idx in range(S):
                # dV = sum_s P~_s^T dO_s (coeff already folded into dO_s)
                dv_acc = dv_acc + jax.lax.dot_general(
                    p_lo[s_idx], do_i[s_idx],
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            dv_scr[:] = dv_acc
            dp = jax.lax.dot_general(
                do_i, v_ref[0],
                dimension_numbers=(((2,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        if dropout_rate > 0.0:
            dp = _apply_keep(dp, dkeep, dropout_rate)
        ds = p * (dp - delta_i[:, :, None])
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q_i.dtype), q_i,
            dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _tiled_bwd_call(
    q, k, v, do_s, lse, delta, offset, *, block_q, block_k, interpret,
    dropout_seed=None, dropout_rate: float = 0.0, coeffs=None,
):
    BH, S, T, d = q.shape
    dv_width = v.shape[-1]
    nq, nk = T // block_q, T // block_k
    factored = coeffs is not None
    c_arr = (
        coeffs.astype(jnp.float32)
        if factored
        else jnp.zeros((BH, S), jnp.float32)
    )
    seed = (
        dropout_seed
        if dropout_seed is not None
        else jnp.zeros((1, 2), jnp.float32)
    )
    off_spec = pl.BlockSpec((1, 1), lambda b, x, y: (0, 0),
                            memory_space=pltpu.SMEM)
    seed_spec = pl.BlockSpec((1, 2), lambda b, x, y: (0, 0),
                             memory_space=pltpu.SMEM)
    c_spec = pl.BlockSpec((BH, S), lambda b, x, y: (0, 0),
                          memory_space=pltpu.SMEM)
    if factored:
        do_spec_q = pl.BlockSpec((1, block_q, dv_width),
                                 lambda b, i, j: (b, i, 0),
                                 memory_space=pltpu.VMEM)
        do_spec_kv = pl.BlockSpec((1, block_q, dv_width),
                                  lambda b, j, i: (b, i, 0),
                                  memory_space=pltpu.VMEM)
    else:
        do_spec_q = pl.BlockSpec((1, S, block_q, dv_width),
                                 lambda b, i, j: (b, 0, i, 0),
                                 memory_space=pltpu.VMEM)
        do_spec_kv = pl.BlockSpec((1, S, block_q, dv_width),
                                  lambda b, j, i: (b, 0, i, 0),
                                  memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(
            _tiled_dq_kernel, dropout_rate=dropout_rate, factored=factored
        ),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, S, block_q, d), lambda b, i, j: (b, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, block_k, d), lambda b, i, j: (b, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dv_width), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            do_spec_q,
            pl.BlockSpec((1, S, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
            off_spec,
            seed_spec,
            c_spec,
        ],
        out_specs=pl.BlockSpec((1, S, block_q, d), lambda b, i, j: (b, 0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, S, T, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((S, block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do_s, lse, delta, offset, seed, c_arr)

    dk, dv = pl.pallas_call(
        functools.partial(
            _tiled_dkv_kernel, dropout_rate=dropout_rate, factored=factored
        ),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, S, block_q, d), lambda b, j, i: (b, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, block_k, d), lambda b, j, i: (b, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dv_width), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            do_spec_kv,
            pl.BlockSpec((1, S, block_q), lambda b, j, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, block_q), lambda b, j, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
            off_spec,
            seed_spec,
            c_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, S, block_k, d), lambda b, j, i: (b, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dv_width), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, dv_width), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((S, block_k, d), jnp.float32),
            pltpu.VMEM((block_k, dv_width), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q, k, v, do_s, lse, delta, offset, seed, c_arr)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref,  # (1, S, block_q, d)
    k_ref,  # (1, S, T, d)
    v_ref,  # (1, T, dv)
    do_ref,  # FACTORED: (1, block_q, dv) shared upstream grad g — the
    #          per-stream grads differ only by the scalar combine
    #          coefficient (dO_s = c_s * g), so dP needs ONE g V^T matmul
    #          scaled per stream instead of S. LEGACY (ring path, where
    #          each stream output has its own cotangent):
    #          (1, S, block_q, dv), coeff folded in.
    lse_ref,  # (1, S, block_q)
    delta_ref,  # (1, S, block_q)     rowsum(dO_s * O_s)
    off_ref,  # (1, 1) float32 SMEM: causal row offset (0 = aligned causal;
    #           +-kTl for ring chunks whose K lives k shards away)
    seed_ref,  # (1, 2) float32 SMEM dropout seed
    c_ref,  # (BH, S) float32 SMEM combine coeffs (read only when factored)
    *refs,  # [bias_ref (block_q, T) if use_bias] then dq_ref (1, S, block_q, d)
    block_k: int,
    dropout_rate: float = 0.0,
    factored: bool = False,
    use_bias: bool = False,
):
    if use_bias:
        bias_ref, dq_ref = refs
    else:
        bias_ref, (dq_ref,) = None, refs
    S, block_q, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    T = k_ref.shape[2]
    nk = T // block_k
    bh_id = pl.program_id(0)  # top-level read (see _tiled_fwd_kernel note)
    i = pl.program_id(1)
    q_start = i * block_q
    off = off_ref[0, 0].astype(jnp.int32)

    q = q_ref[0]
    do = do_ref[0]  # (block_q, dv) factored | (S, block_q, dv) legacy
    lse = lse_ref[0]  # (S, block_q) f32
    delta = delta_ref[0]  # (S, block_q) f32
    scale = 1.0 / math.sqrt(d)

    def body(j, dq):
        def compute(dq):
            k_j = k_ref[0, :, pl.ds(j * block_k, block_k), :]
            v_j = v_ref[0, pl.ds(j * block_k, block_k), :]
            if use_bias:
                # masked entries carry s = NEG_INF, so exp(s - lse) is 0
                # without a select (lse is finite on every row that has
                # any visible key; fully-masked ring rows get p = 1 with
                # an lse that zeroes their chunk weight AND cotangents
                # exactly, so ds/dv contributions stay 0 — same as the
                # select path)
                s = _scores_plus_bias(
                    q, k_j, bias_ref[:, pl.ds(j * block_k, block_k)], scale
                )
                p = jnp.exp(s - lse[:, :, None])
            else:
                s, keep = _masked_scores(
                    q, k_j, q_start, j * block_k, off, scale
                )
                p = jnp.where(keep, jnp.exp(s - lse[:, :, None]), 0.0)
            if factored:
                dp = _scale_streams(
                    jax.lax.dot_general(
                        do, v_j,
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ),
                    c_ref, bh_id, S,
                )  # one matmul, per-stream scalar scale
            else:
                dp = jax.lax.dot_general(
                    do, v_j,
                    dimension_numbers=(((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # (S, block_q, block_k)
            if dropout_rate > 0.0:
                # dP arrives through the dropout: dP~ = mask/keep * (dO V^T)
                dkeep = _keep_mask_block(
                    seed_ref, bh_id, S, q_start, j * block_k,
                    block_q, block_k, dropout_rate, off,
                )
                dp = _apply_keep(dp, dkeep, dropout_rate)
            ds = p * (dp - delta[:, :, None])
            return dq + jax.lax.dot_general(
                ds.astype(k_j.dtype), k_j,
                dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale
        return jax.lax.cond(
            j * block_k <= q_start + block_q - 1 + off, compute, lambda x: x, dq
        )

    dq0 = jnp.zeros((S, block_q, d), jnp.float32)
    dq = jax.lax.fori_loop(0, nk, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref,  # (1, S, T, d)
    k_ref,  # (1, S, block_k, d)
    v_ref,  # (1, block_k, dv)
    do_ref,  # (1, T, dv) factored shared g | (1, S, T, dv) legacy
    #          (see _bwd_dq_kernel)
    lse_ref,  # (1, S, T)
    delta_ref,  # (1, S, T)
    off_ref,  # (1, 1) float32 SMEM causal row offset (see _bwd_dq_kernel)
    seed_ref,  # (1, 2) float32 SMEM dropout seed
    c_ref,  # (BH, S) float32 SMEM combine coeffs (read only when factored)
    *refs,  # [bias_ref (T, block_k) if use_bias] then outputs
    #         dk_ref (1, S, block_k, d), dv_ref (1, block_k, dv)
    block_q: int,
    dropout_rate: float = 0.0,
    factored: bool = False,
    use_bias: bool = False,
):
    if use_bias:
        bias_ref, dk_ref, dv_ref = refs
    else:
        bias_ref, (dk_ref, dv_ref) = None, refs
    S, block_k, d = k_ref.shape[1], k_ref.shape[2], k_ref.shape[3]
    T = q_ref.shape[2]
    dv_width = v_ref.shape[2]
    nq = T // block_q
    bh_id = pl.program_id(0)  # top-level read (see _tiled_fwd_kernel note)
    j = pl.program_id(1)
    k_start = j * block_k
    off = off_ref[0, 0].astype(jnp.int32)

    k = k_ref[0]  # (S, block_k, d)
    scale = 1.0 / math.sqrt(d)

    def body(i, carry):
        dk, dv = carry

        def compute(carry):
            dk, dv = carry
            q_i = q_ref[0, :, pl.ds(i * block_q, block_q), :]
            lse_i = lse_ref[0, :, pl.ds(i * block_q, block_q)]
            delta_i = delta_ref[0, :, pl.ds(i * block_q, block_q)]
            if use_bias:
                # no select: see the twin comment in _bwd_dq_kernel
                s = _scores_plus_bias(
                    q_i, k, bias_ref[pl.ds(i * block_q, block_q), :], scale
                )
                p = jnp.exp(s - lse_i[:, :, None])
            else:
                s, keep = _masked_scores(
                    q_i, k, i * block_q, k_start, off, scale
                )
                p = jnp.where(keep, jnp.exp(s - lse_i[:, :, None]), 0.0)
            p_v = p
            dkeep = None
            if dropout_rate > 0.0:
                dkeep = _keep_mask_block(
                    seed_ref, bh_id, S, i * block_q, k_start,
                    block_q, block_k, dropout_rate, off,
                )
                p_v = _apply_keep(p, dkeep, dropout_rate)  # dropped map P~
            if factored:
                g_i = do_ref[0, pl.ds(i * block_q, block_q), :]  # (bq, dv)
                # dV = sum_s P~_s^T (c_s g) = (sum_s c_s P~_s)^T g — the
                # stream combine is a cheap VPU sum, leaving ONE matmul
                p_c = _combine_streams(p_v, c_ref, bh_id, S).astype(g_i.dtype)
                dv_new = dv + jax.lax.dot_general(
                    p_c, g_i,
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dp = _scale_streams(
                    jax.lax.dot_general(
                        g_i, v_ref[0],
                        dimension_numbers=(((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ),
                    c_ref, bh_id, S,
                )  # one matmul, per-stream scalar scale
            else:
                do_i = do_ref[0, :, pl.ds(i * block_q, block_q), :]
                p_lo = p_v.astype(do_i.dtype)
                # dV = sum_s P~_s^T dO_s (coeff already folded into dO_s).
                # Mosaic can't contract two dims at once, so loop streams
                # statically — S is tiny (1, 2, or n_terms).
                dv_new = dv
                for s_idx in range(S):
                    dv_new = dv_new + jax.lax.dot_general(
                        p_lo[s_idx], do_i[s_idx],
                        dimension_numbers=(((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                dp = jax.lax.dot_general(
                    do_i, v_ref[0],
                    dimension_numbers=(((2,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            if dropout_rate > 0.0:
                dp = _apply_keep(dp, dkeep, dropout_rate)
            ds = p * (dp - delta_i[:, :, None])
            dk_new = dk + jax.lax.dot_general(
                ds.astype(q_i.dtype), q_i,
                dimension_numbers=(((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale
            return dk_new, dv_new

        # skip Q blocks entirely before this K block (causal: no grad flows)
        return jax.lax.cond(i * block_q + block_q - 1 + off >= k_start, compute,
                            lambda c: c, carry)

    dk0 = jnp.zeros((S, block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, dv_width), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# Whole-T fused backward: the (S, T, T) fp32 score/prob/grad
# intermediates must fit VMEM simultaneously (~8 MB at S=2, T=512) — the
# budget scales with the STREAM COUNT, so ndiff's n_terms=4 only takes
# this path at shorter T; past the budget the two-kernel form streams
# blocks instead.
_FUSED_BWD_BUDGET = 2 * 512 * 512  # max S * T * T


def _use_fused_bwd(S: int, T: int) -> bool:
    return S * T * T <= _FUSED_BWD_BUDGET


def _bwd_fused_kernel(
    q_ref,  # (1, S, T, d)
    k_ref,  # (1, S, T, d)
    v_ref,  # (1, T, dv)
    g_ref,  # (1, T, dv) shared upstream grad (factored form only)
    lse_ref,  # (1, S, T)
    delta_ref,  # (1, S, T)
    seed_ref,  # (1, 2) float32 SMEM dropout seed
    c_ref,  # (BH, S) float32 SMEM combine coeffs
    bias_ref,  # (T, T) additive causal mask (aligned: off = 0)
    dq_ref,  # (1, S, T, d)
    dk_ref,  # (1, S, T, d)
    dv_ref,  # (1, T, dv)
    *,
    dropout_rate: float = 0.0,
):
    """dQ, dK, dV in ONE program per (b*H): within _FUSED_BWD_BUDGET the
    full score matrix fits VMEM, so the softmax recompute (the QK^T
    matmul, the exp — the kernels' VPU floor — and the dP matmul) runs
    ONCE instead of once in each of the dq and dkv kernels, and q/k/v/g
    are read once. Straight-line code, no grid loops: the whole
    backward for one head is a single fused region."""
    S, T, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    bh_id = pl.program_id(0)
    q = q_ref[0]  # (S, T, d)
    k = k_ref[0]
    v = v_ref[0]  # (T, dv)
    g = g_ref[0]  # (T, dv)
    lse = lse_ref[0]  # (S, T) f32
    delta = delta_ref[0]  # (S, T) f32
    scale = 1.0 / math.sqrt(d)

    s = _scores_plus_bias(q, k, bias_ref[:, :], scale)  # (S, T, T) f32
    p = jnp.exp(s - lse[:, :, None])  # masked entries -> exp(-1e30) = 0
    p_v = p
    dkeep = None
    if dropout_rate > 0.0:
        dkeep = _keep_mask_block(
            seed_ref, bh_id, S, 0, 0, T, T, dropout_rate, None
        )
        p_v = _apply_keep(p, dkeep, dropout_rate)  # dropped map P~
    # dV = (sum_s c_s P~_s)^T g — one matmul after the VPU stream combine
    p_c = _combine_streams(p_v, c_ref, bh_id, S).astype(g.dtype)
    dv_ref[0] = jax.lax.dot_general(
        p_c, g,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(dv_ref.dtype)
    # dP_s = c_s * (g V^T), computed once and scaled per stream
    dp = _scale_streams(
        jax.lax.dot_general(
            g, v,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ),
        c_ref, bh_id, S,
    )
    if dropout_rate > 0.0:
        dp = _apply_keep(dp, dkeep, dropout_rate)
    ds = (p * (dp - delta[:, :, None])).astype(q.dtype)
    dq_ref[0] = (
        jax.lax.dot_general(
            ds, k,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
    ).astype(dq_ref.dtype)
    dk_ref[0] = (
        jax.lax.dot_general(
            ds, q,
            dimension_numbers=(((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale
    ).astype(dk_ref.dtype)


def _fused_bwd_call(
    q, k, v, g, lse, delta, *, interpret,
    dropout_seed=None, dropout_rate: float = 0.0, coeffs=None,
):
    BH, S, T, d = q.shape
    dv_width = v.shape[-1]
    seed = (
        dropout_seed
        if dropout_seed is not None
        else jnp.zeros((1, 2), jnp.float32)
    )
    def spec4(shape):
        return pl.BlockSpec(shape, lambda b: (b, 0, 0, 0),
                            memory_space=pltpu.VMEM)

    def spec3(shape):
        return pl.BlockSpec(shape, lambda b: (b, 0, 0),
                            memory_space=pltpu.VMEM)

    return pl.pallas_call(
        functools.partial(_bwd_fused_kernel, dropout_rate=dropout_rate),
        grid=(BH,),
        in_specs=[
            spec4((1, S, T, d)),
            spec4((1, S, T, d)),
            spec3((1, T, dv_width)),
            spec3((1, T, dv_width)),
            spec3((1, S, T)),
            spec3((1, S, T)),
            pl.BlockSpec((1, 2), lambda b: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((BH, S), lambda b: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((T, T), lambda b: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            spec4((1, S, T, d)),
            spec4((1, S, T, d)),
            spec3((1, T, dv_width)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, S, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, dv_width), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)
        ),
        interpret=interpret,
    )(q, k, v, g, lse, delta, seed, coeffs.astype(jnp.float32),
      causal_bias(T, 0))


def _bwd_call(
    q, k, v, do_s, lse, delta, offset=None, *,
    block_q: int, block_k: int, interpret: bool,
    dropout_seed=None, dropout_rate: float = 0.0, coeffs=None,
):
    """``coeffs`` (BH, S) switches the kernels to the FACTORED form:
    ``do_s`` is then the SHARED upstream grad g of shape (BH, T, dv) and
    the per-stream grads are recovered in-kernel as c_s * g — one dP/dV
    matmul instead of S, and S-fold less dO streamed. ``coeffs=None`` is
    the legacy per-stream form (the ring path's chunk cotangents cannot
    factor)."""
    BH, S, T, d = q.shape
    dv_width = v.shape[-1]
    nq, nk = T // block_q, T // block_k
    factored = coeffs is not None
    aligned = offset is None  # the main (non-ring) path: causal off = 0
    if offset is None:
        offset = jnp.zeros((1, 1), jnp.float32)
    seed = (
        dropout_seed
        if dropout_seed is not None
        else jnp.zeros((1, 2), jnp.float32)
    )
    if aligned and factored and _use_fused_bwd(S, T):
        # whole-T single-program backward: one softmax recompute serves
        # dq, dk AND dv (see _bwd_fused_kernel)
        return _fused_bwd_call(
            q, k, v, do_s, lse, delta, interpret=interpret,
            dropout_seed=seed, dropout_rate=dropout_rate, coeffs=coeffs,
        )
    if T > _BWD_KV_TILE_THRESHOLD:
        return _tiled_bwd_call(
            q, k, v, do_s, lse, delta, offset,
            block_q=block_q, block_k=block_k, interpret=interpret,
            dropout_seed=seed, dropout_rate=dropout_rate, coeffs=coeffs,
        )
    c_arr = (
        coeffs.astype(jnp.float32)
        if factored
        else jnp.zeros((BH, S), jnp.float32)
    )
    use_bias = T <= _BIAS_MAX_T
    bias = causal_bias(T, offset[0, 0].astype(jnp.int32)) if use_bias else None
    off_spec = pl.BlockSpec((1, 1), lambda b, i: (0, 0), memory_space=pltpu.SMEM)
    seed_spec = pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM)
    c_spec = pl.BlockSpec((BH, S), lambda b, i: (0, 0), memory_space=pltpu.SMEM)
    if factored:
        do_spec_q = pl.BlockSpec((1, block_q, dv_width),
                                 lambda b, i: (b, i, 0),
                                 memory_space=pltpu.VMEM)
        do_spec_kv = pl.BlockSpec((1, T, dv_width), lambda b, j: (b, 0, 0),
                                  memory_space=pltpu.VMEM)
    else:
        do_spec_q = pl.BlockSpec((1, S, block_q, dv_width),
                                 lambda b, i: (b, 0, i, 0),
                                 memory_space=pltpu.VMEM)
        do_spec_kv = pl.BlockSpec((1, S, T, dv_width), lambda b, j: (b, 0, 0, 0),
                                  memory_space=pltpu.VMEM)

    dq_in_specs = [
        pl.BlockSpec((1, S, block_q, d), lambda b, i: (b, 0, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, S, T, d), lambda b, i: (b, 0, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, T, dv_width), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        do_spec_q,
        pl.BlockSpec((1, S, block_q), lambda b, i: (b, 0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, S, block_q), lambda b, i: (b, 0, i),
                     memory_space=pltpu.VMEM),
        off_spec,
        seed_spec,
        c_spec,
    ]
    dq_inputs = [q, k, v, do_s, lse, delta, offset, seed, c_arr]
    if use_bias:
        dq_in_specs.append(
            pl.BlockSpec((block_q, T), lambda b, i: (i, 0),
                         memory_space=pltpu.VMEM)
        )
        dq_inputs.append(bias)
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=block_k, dropout_rate=dropout_rate,
            factored=factored, use_bias=use_bias,
        ),
        grid=(BH, nq),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, S, block_q, d), lambda b, i: (b, 0, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, S, T, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(*dq_inputs)

    dkv_in_specs = [
        pl.BlockSpec((1, S, T, d), lambda b, j: (b, 0, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, S, block_k, d), lambda b, j: (b, 0, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, dv_width), lambda b, j: (b, j, 0),
                     memory_space=pltpu.VMEM),
        do_spec_kv,
        pl.BlockSpec((1, S, T), lambda b, j: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, S, T), lambda b, j: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        off_spec,
        seed_spec,
        c_spec,
    ]
    dkv_inputs = [q, k, v, do_s, lse, delta, offset, seed, c_arr]
    if use_bias:
        dkv_in_specs.append(
            pl.BlockSpec((T, block_k), lambda b, j: (0, j),
                         memory_space=pltpu.VMEM)
        )
        dkv_inputs.append(bias)
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=block_q, dropout_rate=dropout_rate,
            factored=factored, use_bias=use_bias,
        ),
        grid=(BH, nk),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, S, block_k, d), lambda b, j: (b, 0, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, dv_width), lambda b, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, T, d), q.dtype),
            jax.ShapeDtypeStruct((BH, T, dv_width), v.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(*dkv_inputs)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper over (BH, S, T, d) layout
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _flash(q, k, v, coeffs, seed, blocks, interpret, rate=0.0):
    """``blocks`` = (block_q, block_k, block_q_train, block_k_train).
    The inference primal and the differentiated path want different
    tilings, so they are tuned independently. ``seed`` is the (1, 2)
    float32 dropout seed (dropout_seed_from_rng); ``rate`` the static
    attention-prob dropout rate — both forward and backward regenerate
    the same counter-based masks from (seed, global coords)."""
    out, _, _ = _fwd_call(
        q, k, v, coeffs,
        block_q=blocks[0], block_k=blocks[1],
        save_residuals=False, interpret=interpret,
        dropout_seed=seed, dropout_rate=rate,
    )
    return out


def _flash_fwd(q, k, v, coeffs, seed, blocks, interpret, rate=0.0):
    out, o_all, lse = _fwd_call(
        q, k, v, coeffs,
        block_q=blocks[2], block_k=blocks[3],
        save_residuals=True, interpret=interpret,
        dropout_seed=seed, dropout_rate=rate,
    )
    return out, (q, k, v, coeffs, seed, o_all, lse)


def _flash_bwd(blocks, interpret, rate, res, g):
    q, k, v, coeffs, seed, o_all, lse = res
    g32 = g.astype(jnp.float32)
    o32 = o_all.astype(jnp.float32)
    c32 = coeffs.astype(jnp.float32)
    # one contraction feeds both residual quantities:
    #   base[bh, s, t] = <g_t, O_s,t> over the head dim
    #   dcoeffs[bh, s] = <g, O_s>           = base.sum(t)
    #   delta_s        = rowsum(dO_s * O_s) = c_s * base  (dO_s = c_s g)
    # delta stays valid with dropout: rowsum(dP~ . P) = rowsum(dA . P~)
    # = rowsum(dO . O) since elementwise products commute — the same
    # residuals serve both regimes.
    base = jnp.einsum("btd,bstd->bst", g32, o32)
    dcoeffs = base.sum(-1)
    delta = base * c32[:, :, None]
    # FACTORED backward: the kernels take the shared g once and scale by
    # c_s in-SMEM — S-fold less dO traffic and one dP/dV matmul each
    # (the (BH, S, T, dv) do_s materialization this replaced was also
    # pure HBM waste)
    dq, dk, dv = _bwd_call(
        q, k, v, g.astype(q.dtype), lse, delta,
        block_q=blocks[2], block_k=blocks[3], interpret=interpret,
        dropout_seed=seed, dropout_rate=rate, coeffs=c32,
    )
    return dq, dk, dv, dcoeffs.astype(coeffs.dtype), jnp.zeros_like(seed)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Chunk op: per-stream (O_s, lse_s) with a causal row offset — the building
# block for ring (sequence-parallel) flash attention
# ---------------------------------------------------------------------------


def _chunk_fwd_call(q, k, v, offset, *, block_q, block_k, interpret,
                    dropout_seed=None, dropout_rate: float = 0.0):
    """Per-stream (o_all, lse) with offset-causal masking — the unified
    forward kernel in its no-combine mode. off = +Tl*k means K lives k
    shards earlier in the ring (fully visible once off >= T); large
    negative off masks everything (the chunk then contributes weight
    exp(-inf) = 0 at merge time)."""
    BH, S, T, d = q.shape
    dv = v.shape[-1]
    nq = T // block_q
    seed = (
        dropout_seed
        if dropout_seed is not None
        else jnp.zeros((1, 2), jnp.float32)
    )
    if T > _KV_TILE_THRESHOLD:
        return _tiled_fwd_call(
            q, k, v, offset, None,
            block_q=block_q, block_k=block_k,
            save_residuals=True, emit_combined=False, interpret=interpret,
            dropout_seed=seed, dropout_rate=dropout_rate,
        )
    use_bias = T <= _BIAS_MAX_T
    in_specs = [
        pl.BlockSpec((1, S, block_q, d), lambda b, i: (b, 0, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, S, T, d), lambda b, i: (b, 0, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, T, dv), lambda b, i: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, 1), lambda b, i: (0, 0), memory_space=pltpu.SMEM),
        pl.BlockSpec((1, 2), lambda b, i: (0, 0), memory_space=pltpu.SMEM),
    ]
    inputs = [q, k, v, offset, seed]
    if use_bias:
        # the bias bakes the TRACED ring offset in — computed once per
        # chunk call instead of per (b*H) program
        in_specs.append(
            pl.BlockSpec((block_q, T), lambda b, i: (i, 0),
                         memory_space=pltpu.VMEM)
        )
        inputs.append(causal_bias(T, offset[0, 0].astype(jnp.int32)))
    return pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_k=block_k, save_residuals=True,
            emit_combined=False, dropout_rate=dropout_rate,
            use_bias=use_bias,
        ),
        grid=(BH, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, S, block_q, dv), lambda b, i: (b, 0, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, block_q), lambda b, i: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, T, dv), q.dtype),
            jax.ShapeDtypeStruct((BH, S, T), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")
        ),
        interpret=interpret,
    )(*inputs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_chunk_attention(q, k, v, offset, seed, blocks, interpret, rate=0.0):
    """Per-stream offset-causal flash chunk: ``(O_s, lse_s)`` for
    ``O_s = [dropout](softmax(Q_s K_s^T / sqrt(d) + offset-causal
    mask)) @ V``.

    q/k: (BH, S, T, d); v: (BH, T, dv); offset: (1, 1) float32 (traced —
    inside a shard_map ring it is a function of axis_index); ``seed`` a
    (1, 2) float32 dropout seed (zeros when rate == 0). Returns
    (o_all (BH, S, T, dv), lse (BH, S, T)); lse accumulates the UNdropped
    probabilities, so chunks still combine exactly via the running
    logsumexp merge (parallel/ring.py) — softmax-then-dropout semantics
    globally. Dropout masks hash (row, col - off), which is unique per
    (q, k) pair across the ring rotation on a given device."""
    return _chunk_fwd_call(
        q, k, v, offset, block_q=blocks[0], block_k=blocks[1],
        interpret=interpret, dropout_seed=seed, dropout_rate=rate,
    )


def _flash_chunk_fwd(q, k, v, offset, seed, blocks, interpret, rate=0.0):
    o_all, lse = _chunk_fwd_call(
        q, k, v, offset, block_q=blocks[0], block_k=blocks[1],
        interpret=interpret, dropout_seed=seed, dropout_rate=rate,
    )
    return (o_all, lse), (q, k, v, offset, seed, o_all, lse)


def _flash_chunk_bwd(blocks, interpret, rate, res, ct):
    q, k, v, offset, seed, o_all, lse = res
    do, dlse = ct  # cotangents for both outputs
    do32 = do.astype(jnp.float32)
    # dS = P * (dP_raw - delta + dlse): the lse cotangent folds into the
    # delta term of the standard flash backward (dlse_i distributes over the
    # row's probabilities). With dropout, only the dP term is masked (the
    # lse path sees undropped probabilities), which the kernels implement.
    delta_eff = (
        jnp.einsum("bstd,bstd->bst", do32, o_all.astype(jnp.float32))
        - dlse.astype(jnp.float32)
    )
    dq, dk, dv = _bwd_call(
        q, k, v, do.astype(q.dtype), lse, delta_eff, offset,
        block_q=blocks[2], block_k=blocks[3], interpret=interpret,
        dropout_seed=seed, dropout_rate=rate,
    )
    return dq, dk, dv, jnp.zeros_like(offset), jnp.zeros_like(seed)


flash_chunk_attention.defvjp(_flash_chunk_fwd, _flash_chunk_bwd)


# ---------------------------------------------------------------------------
# Public API — model-facing layouts (matching ops/attention.py conventions)
# ---------------------------------------------------------------------------


def multi_stream_flash_attention(
    qs: jnp.ndarray,  # (S, B, T, H, d)
    ks: jnp.ndarray,  # (S, B, T, H, d)
    v: jnp.ndarray,  # (B, T, H, dv)
    coeffs: jnp.ndarray,  # (S, H) float32
    *,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_q_train: Optional[int] = None,
    block_k_train: Optional[int] = None,
    interpret: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """Fused causal attention: ``sum_s coeffs[s,h] * softmax(Q_s K_s^T /
    sqrt(d)) @ V`` without materializing any T x T map. Returns
    (B, T, H, dv).

    ``dropout_rate`` > 0 with a ``dropout_rng`` key applies attention-
    probability dropout INSIDE the kernel (each softmax map dropped
    independently after normalization, inverted scaling — the reference
    semantics, diff_transformer.py:58-67) via a counter-based hash of the
    global (stream, b*H+h, row, col) position, so forward and backward
    regenerate identical masks and no T x T mask is ever materialized.
    Without a key the rate is inert (eval semantics, like ops/dropout.py).

    Block defaults resolve per device kind (:func:`default_blocks`) with
    one T-dependent cap below. On v5e the tuned tiles are (512, 1024)
    for the no-grad primal and for the training path — the 1024-wide K
    train tile became compilable once the kernels switched to bf16 MXU
    operands (half the VMEM per tile) and measured 5-29% faster than
    512-square in bare-op sweeps (tools/flash_sweep.py). BUT in the
    RESIDENT backward region (1024 < T <= _BWD_KV_TILE_THRESHOLD, where
    the bwd kernels hold full-T q/do) the wide tile exhausts v5e's scoped
    VMEM under the full model, so the default train K tile is capped to
    512 there; the KV-tiled kernels past the threshold hold O(block)
    state and keep the wide tile. Unknown TPU kinds fall back to
    256-tiles."""
    if interpret is None:
        interpret = _auto_interpret()
    S, B, T, H, d = qs.shape
    dv = v.shape[-1]
    # (S, B, T, H, d) -> (B*H, S, T, d)
    q_r = qs.transpose(1, 3, 0, 2, 4).reshape(B * H, S, T, d)
    k_r = ks.transpose(1, 3, 0, 2, 4).reshape(B * H, S, T, d)
    v_r = v.transpose(0, 2, 1, 3).reshape(B * H, T, dv)
    out = multi_stream_flash_attention_bh(
        q_r, k_r, v_r, coeffs, B, H,
        block_q=block_q, block_k=block_k,
        block_q_train=block_q_train, block_k_train=block_k_train,
        interpret=interpret,
        dropout_rate=dropout_rate, dropout_rng=dropout_rng,
    )  # (BH, T, dv)
    return out.reshape(B, H, T, dv).transpose(0, 2, 1, 3)


def multi_stream_flash_attention_bh(
    q_r: jnp.ndarray,  # (B*H, S, T, d) — the kernel's native layout
    k_r: jnp.ndarray,  # (B*H, S, T, d)
    v_r: jnp.ndarray,  # (B*H, T, dv)
    coeffs: jnp.ndarray,  # (S, H) float32
    B: int,
    H: int,
    *,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    block_q_train: Optional[int] = None,
    block_k_train: Optional[int] = None,
    interpret: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """:func:`multi_stream_flash_attention` taking the kernel's native
    (B*H, S, T, d) layout directly and returning (B*H, T, dv). Callers
    that can emit their projections in this layout (einsum
    ``"bte,sehd->bhstd"`` + free reshape) skip the materialized
    transposes of the (S, B, T, H, d) entry — profiled ~0.5-1 ms of copy
    ops at recipe scale (within run-to-run noise on the full step, but
    visible in the per-op trace)."""
    if interpret is None:
        interpret = _auto_interpret()
    dq, dk, dqt, dkt = default_blocks()
    BH, S, T, d = q_r.shape
    bkt = block_k_train if block_k_train is not None else dkt
    if 1024 < T <= _BWD_KV_TILE_THRESHOLD and block_k_train is None:
        # the RESIDENT backward kernels hold full-T q/do plus the K/V
        # block: with the 1024-wide train K tile their fp32 p/dp/ds
        # blocks exceed v5e's 16M scoped VMEM from T=2048 (measured
        # under the full model; the bare-op sweep happens to fit;
        # re-verified round 3 AFTER the factored backward halved the dO
        # traffic — the wide tile still fails to compile at T=2048, so
        # the clamp is not stale). The
        # KV-tiled kernels past _BWD_KV_TILE_THRESHOLD hold only O(block)
        # state, so they keep the wide tile; lowering that knob moves
        # this clamp region with it.
        bkt = min(bkt, 512)
    blocks = (
        _pick_block(block_q if block_q is not None else dq, T),
        _pick_block(block_k if block_k is not None else dk, T),
        _pick_block(block_q_train if block_q_train is not None else dqt, T),
        _pick_block(bkt, T),
    )
    c_r = jnp.broadcast_to(
        coeffs.astype(jnp.float32).T[None], (B, H, S)
    ).reshape(B * H, S)
    if dropout_rate > 0.0 and dropout_rng is not None:
        seed = dropout_seed_from_rng(dropout_rng)
        rate = float(dropout_rate)
    else:
        seed = jnp.zeros((1, 2), jnp.float32)
        rate = 0.0
    return _flash(q_r, k_r, v_r, c_r, seed, blocks, interpret, rate)


# ---------------------------------------------------------------------------
# Token-major (tm) kernels: per-stream (B, T, H, d) operands in and
# (B, T, H, dv) out — the PROJECTION-NATIVE layout.
#
# The head-major entry above needs its operands as (BH, S, T, d), but a
# projection matmul physically produces token-major data: x @ W is
# (B, T, H*d), and the transpose to head-major is a materialized XLA copy
# (~660 MB/step HBM->HBM at recipe scale, per-op profile round 4). Worse,
# the head-major ATTENTION OUTPUT makes the downstream GroupLayerNorm
# reduce over a strided concat dim (measured 4.5 ms/step of stat reduces
# alone) and the out-projection re-transpose. These kernels instead read
# per-stream token-major arrays directly via squeezed BlockSpec dims
# (block (None, bq, None, d) on a (B, T, H, d) array -> a clean (bq, d)
# VMEM tile DMA'd with an H*d row stride) and write the output token-major,
# so the whole attention block — projections, kernel, GLN, out-proj, and
# every gradient — runs transpose-free.
#
# Scope (use_tm): the recipe-hot region only — dropout 0.0, T small enough
# for the additive-bias resident forward AND the fused whole-T backward
# (T and S within the _TM_BWD_MAX_* envelope). Everything else (long context,
# dropout, ring chunks) stays on the head-major path; dispatch via use_tm.
# ---------------------------------------------------------------------------

# Whole-T tm backward admission, SEPARATE from the head-major
# _FUSED_BWD_BUDGET. Two measured walls (round 5, v5e, recipe widths):
#   - streams scale gently: the kernel walks (head, stream) pairs
#     sequentially, so S only grows the resident per-stream q/k/dq/dk
#     arrays (~0.4 MB each) — S=4 at T=512 compiles and runs inside
#     _TM_VMEM_LIMIT with 256-row forward blocks (the r4 2*512*512 cap
#     was a holdover from the head-major straight-line kernel, not a tm
#     measurement), so ndiff's n_terms=4 recipe dispatches token-major
#     like diff/control instead of paying the bh transpose copies;
#   - T scales hard: the backward's T x T fp32 score/prob transients are
#     duplicated across the unrolled head loop, so T=1024 at S=1 blows
#     scoped VMEM (73 MB measured). T stays capped at 512; longer T
#     belongs to the head-major / KV-tiled paths.
_TM_BWD_MAX_T = 512
_TM_BWD_MAX_S = 4


def use_tm(S: int, T: int, rate: float) -> bool:
    """True when the token-major kernels cover this config: no attention
    dropout (the tm kernels drop the counter-based mask machinery), the
    resident additive-bias forward applies, and the whole-T fused backward
    fits its measured VMEM envelope (see the admission constants above)."""
    return rate == 0.0 and T <= _TM_BWD_MAX_T and S <= _TM_BWD_MAX_S


def _tm_bias(T: int) -> jnp.ndarray:
    """bf16 additive causal mask for the tm kernels — half the VMEM of the
    fp32 :func:`causal_bias` (the kernels upcast when adding to the fp32
    scores; bf16 rounds NEG_INF to ~-1.0e30, still an exact zero after
    exp)."""
    return causal_bias(T, 0).astype(jnp.bfloat16)


def _tm_fwd_kernel(
    *refs,
    S: int,
    H: int,
    save_residuals: bool,
):
    """Single-pass (full-T) forward over token-major refs, one program
    per (batch row, q block), all H heads in-program.

    refs: q_0..q_{S-1} (bq, H*d) | k_0..k_{S-1} (T, H*d) | v (T, H*dv) |
    bias (bq, T) bf16 | c (BH, S) SMEM | out (bq, H*dv)
    [| oall (H, S, bq, dv), lse (bq, H*S) when save_residuals].

    The head dim rides FLATTENED into the lane dim (one lane slice per
    head) because Mosaic rejects sublane-strided stores of converted
    (f32 -> bf16) values — the (bq, H, d) mid-dim form fails with
    "infer-vector-layout: unsupported shape cast" at the output store,
    while lane slicing + a single concatenated store compiles (probed on
    v5e, round 4). The (head, stream) loops are statically unrolled —
    each iteration is a plain (bq, d) x (T, d) attention. K is full-T
    resident and T <= _BIAS_MAX_T, so the softmax needs no online block
    loop: one (bq, T) fp32 score pass per (head, stream). lse packs
    (head, stream) into ITS lane dim too ((bq, H*S), column h*S + s) —
    the (H, bq, S) form pads S=2 lanes to 128 and wastes ~1 MB of VMEM
    per buffer."""
    q_refs, refs = refs[:S], refs[S:]
    k_refs, refs = refs[:S], refs[S:]
    v_ref, bias_ref, c_ref, *outs = refs
    d = q_refs[0].shape[-1] // H
    dv = v_ref.shape[-1] // H
    b = pl.program_id(0)
    scale = 1.0 / math.sqrt(d)
    bias = bias_ref[...].astype(jnp.float32)  # (bq, T)

    out_ref = outs[0]
    out_cols = []
    lse_cols = []
    for h in range(H):
        v_h = v_ref[:, h * dv : (h + 1) * dv]  # (T, dv)
        combined = None
        for s_i in range(S):
            q_h = q_refs[s_i][:, h * d : (h + 1) * d]  # (bq, d)
            k_h = k_refs[s_i][:, h * d : (h + 1) * d]  # (T, d)
            sm = jax.lax.dot_general(
                q_h, k_h,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + bias  # (bq, T) f32
            m = jnp.max(sm, axis=-1, keepdims=True)  # (bq, 1)
            p = jnp.exp(sm - m)
            l = jnp.sum(p, axis=-1, keepdims=True)
            l_safe = jnp.maximum(l, 1e-30)
            pv = jax.lax.dot_general(
                p.astype(v_h.dtype), v_h,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (bq, dv)
            o_sh = pv / l_safe
            c_sh = c_ref[b * H + h, s_i]
            combined = (
                o_sh * c_sh if combined is None else combined + o_sh * c_sh
            )
            if save_residuals:
                oall_ref = outs[1]
                oall_ref[h, s_i] = o_sh.astype(oall_ref.dtype)
                lse_cols.append(m + jnp.log(l_safe))  # (bq, 1)
        out_cols.append(combined.astype(out_ref.dtype))
    out_ref[...] = jnp.concatenate(out_cols, axis=1)  # (bq, H*dv)
    if save_residuals:
        lse_ref = outs[2]
        lse_ref[...] = jnp.concatenate(lse_cols, axis=1)  # (bq, H*S) f32


def _tm_fwd_call(
    qs, ks, v, coeffs, *, H: int, block_q: int, save_residuals: bool,
    interpret: bool
):
    """qs/ks: tuples of S (B, T, H*d) arrays (raw projection outputs);
    v (B, T, H*dv); coeffs (B*H, S) fp32; ``H`` static. Returns
    (out (B, T, H*dv) [, oall (B, H, S, T, dv), lse (B, T, H*S)])."""
    S = len(qs)
    B, T, Hd = qs[0].shape
    d = Hd // H
    dv = v.shape[-1] // H
    BH = B * H
    block_q = _pick_block(block_q, T)
    nq = T // block_q

    qspec = pl.BlockSpec(
        (None, block_q, H * d), lambda b, i: (b, i, 0),
        memory_space=pltpu.VMEM,
    )
    kspec = pl.BlockSpec(
        (None, T, H * d), lambda b, i: (b, 0, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [qspec] * S + [kspec] * S + [
        pl.BlockSpec(
            (None, T, H * dv), lambda b, i: (b, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        pl.BlockSpec((block_q, T), lambda b, i: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((BH, S), lambda b, i: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    out_shapes = [jax.ShapeDtypeStruct((B, T, H * dv), qs[0].dtype)]
    out_specs = [
        pl.BlockSpec(
            (None, block_q, H * dv), lambda b, i: (b, i, 0),
            memory_space=pltpu.VMEM,
        ),
    ]
    if save_residuals:
        out_shapes += [
            jax.ShapeDtypeStruct((B, H, S, T, dv), qs[0].dtype),
            jax.ShapeDtypeStruct((B, T, H * S), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec(
                (None, H, S, block_q, dv),
                lambda b, i: (b, 0, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, block_q, H * S), lambda b, i: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
    results = pl.pallas_call(
        functools.partial(
            _tm_fwd_kernel, S=S, H=H, save_residuals=save_residuals
        ),
        grid=(B, nq),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=_TM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*qs, *ks, v, _tm_bias(T), coeffs.astype(jnp.float32))
    if save_residuals:
        return results
    return results[0], None, None


def _tm_bwd_columns(
    q_refs, k_refs, v_ref, g_ref, lse_ref, delta_ref, c_ref, bias,
    *, S: int, H: int, s_list: tuple, out_dtype,
):
    """The factored whole-T backward math shared by the per-array and
    packed tm kernels: per (head, listed stream) gradient column groups.
    Returns (dq_cols, dk_cols, dv_cols) — dq_cols[j]/dk_cols[j] are
    h-ordered lists of (T, d) columns for stream s_list[j]; dv_cols is
    the h-ordered list of (T, dv) columns (dV summed over the listed
    streams). g V^T runs once per head and is scaled per stream; each
    stream's softmax recompute (the exp floor) happens exactly once."""
    d = q_refs[0].shape[-1] // H
    dv = v_ref.shape[-1] // H
    b = pl.program_id(0)
    scale = 1.0 / math.sqrt(d)

    dq_cols = [[] for _ in s_list]
    dk_cols = [[] for _ in s_list]
    dv_cols = []
    for h in range(H):
        v_h = v_ref[:, h * dv : (h + 1) * dv]  # (T, dv)
        g_h = g_ref[:, h * dv : (h + 1) * dv]  # (T, dv)
        gv = jax.lax.dot_general(
            g_h, v_h,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (T, T) f32 — once per head, shared by every listed stream
        dv_h = None
        for j, s_idx in enumerate(s_list):
            col = h * S + s_idx
            lse_h = lse_ref[:, col : col + 1]  # (T, 1) f32
            delta_h = delta_ref[:, col : col + 1]  # (T, 1) f32
            q_h = q_refs[j][:, h * d : (h + 1) * d]  # (T, d)
            k_h = k_refs[j][:, h * d : (h + 1) * d]
            sm = jax.lax.dot_general(
                q_h, k_h,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + bias
            p = jnp.exp(sm - lse_h)  # (T, T)
            c_sh = c_ref[b * H + h, s_idx]
            ds = (p * (gv * c_sh - delta_h)).astype(q_h.dtype)
            dq_cols[j].append(
                (
                    jax.lax.dot_general(
                        ds, k_h,
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * scale
                ).astype(out_dtype)
            )
            dk_cols[j].append(
                (
                    jax.lax.dot_general(
                        ds, q_h,
                        dimension_numbers=(((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    ) * scale
                ).astype(out_dtype)
            )
            pc = p * c_sh
            dv_h = pc if dv_h is None else dv_h + pc
        dv_cols.append(
            jax.lax.dot_general(
                dv_h.astype(g_h.dtype), g_h,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(out_dtype)
        )
    return dq_cols, dk_cols, dv_cols


def _tm_bwd_kernel(*refs, S: int, H: int, s_list: tuple):
    """Whole-T backward for the streams in ``s_list`` over token-major
    refs, one program per batch row — the factored math of
    :func:`_bwd_fused_kernel` (see :func:`_tm_bwd_columns`); outputs are
    stored per-stream as lane concats (see _tm_fwd_kernel on why the
    mid-dim form cannot store).

    refs: q_s (T, H*d) per listed stream | k_s likewise | v (T, H*dv) |
    g (T, H*dv) | lse (T, H*S) | delta (T, H*S) | c (BH, S) SMEM |
    bias (T, T) bf16 | dq_s per stream | dk_s per stream | dv (T, H*dv)."""
    ns = len(s_list)
    q_refs, refs = refs[:ns], refs[ns:]
    k_refs, refs = refs[:ns], refs[ns:]
    (v_ref, g_ref, lse_ref, delta_ref, c_ref, bias_ref, *outs) = refs
    dq_refs, dk_refs, dv_ref = outs[:ns], outs[ns : 2 * ns], outs[2 * ns]
    dq_cols, dk_cols, dv_cols = _tm_bwd_columns(
        q_refs, k_refs, v_ref, g_ref, lse_ref, delta_ref, c_ref,
        bias_ref[...].astype(jnp.float32),
        S=S, H=H, s_list=s_list, out_dtype=dq_refs[0].dtype,
    )
    for j in range(ns):
        dq_refs[j][...] = jnp.concatenate(dq_cols[j], axis=1)
        dk_refs[j][...] = jnp.concatenate(dk_cols[j], axis=1)
    dv_ref[...] = jnp.concatenate(dv_cols, axis=1)


def _tm_bwd_call(qs, ks, v, g, lse, delta, coeffs, *, H: int, interpret: bool):
    """qs/ks/v/g: flat (B, T, H*width); lse/delta: (B, T, H*S) fp32.
    All streams in ONE pallas call (the g V^T matmul then runs once per
    head): the call raises the kernel's scoped-VMEM budget via
    vmem_limit_bytes — the recipe-shape footprint is ~17-18 MB against
    the 16 MB default (measured round 4), comfortably inside v5e's
    physical VMEM. Returns per-stream flat token-major (dqs, dks, dv)."""
    S = len(qs)
    B, T, Hd = qs[0].shape
    Hdv = v.shape[-1]
    BH = B * H

    qspec = pl.BlockSpec(
        (None, T, Hd), lambda b: (b, 0, 0), memory_space=pltpu.VMEM
    )
    vspec = pl.BlockSpec(
        (None, T, Hdv), lambda b: (b, 0, 0), memory_space=pltpu.VMEM
    )
    stspec = pl.BlockSpec(
        (None, T, H * S), lambda b: (b, 0, 0), memory_space=pltpu.VMEM
    )
    results = pl.pallas_call(
        functools.partial(
            _tm_bwd_kernel, S=S, H=H, s_list=tuple(range(S))
        ),
        grid=(B,),
        in_specs=[qspec] * S + [qspec] * S + [
            vspec, vspec, stspec, stspec,
            pl.BlockSpec((BH, S), lambda b: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((T, T), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[qspec] * S + [qspec] * S + [vspec],
        out_shape=(
            [jax.ShapeDtypeStruct((B, T, Hd), qs[0].dtype)] * S
            + [jax.ShapeDtypeStruct((B, T, Hd), qs[0].dtype)] * S
            + [jax.ShapeDtypeStruct((B, T, Hdv), v.dtype)]
        ),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_TM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*qs, *ks, v, g, lse, delta, coeffs.astype(jnp.float32), _tm_bias(T))
    dqs = tuple(results[:S])
    dks = tuple(results[S : 2 * S])
    return dqs, dks, results[2 * S]


# Scoped-VMEM budget for ALL tm pallas_calls (fwd and bwd, per-array and
# packed): 28 MB, ~1/4 of v5e's 128 MB physical VMEM (the 16 MB default
# is conservative). Defined once because the training q-block size below
# is only compilable under it — deriving one from the other keeps them
# from drifting apart (advisor, round 4).
_TM_VMEM_LIMIT = 28 * 1024 * 1024

# Training-forward q-block rows. The residual-saving forward carries
# oall + lse blocks on top of the compute blocks; at the recipe shape the
# 512-row block needs ~18 MB of scoped VMEM at S<=2 (measured round 4)
# but 32.3 MB at S=4 — over the limit. Rather than raising the limit
# (probed round 5 on v5e, S=4: 28 MB/block-256 = 16.3 ms, 40 MB/block-512
# = 18.0 ms, 48 MB/block-512 = 24.9 ms — extra scoped VMEM *slows* the
# kernel by squeezing pipelining headroom), S>=3 drops to 256-row blocks
# under the unchanged limit, which is also the fastest point. At S<=2,
# 512 stays ~0.5% faster than 256 (fewer programs, one bias stripe).
_TM_TRAIN_BLOCK_Q = 512 if _TM_VMEM_LIMIT >= 20 * 1024 * 1024 else 256


def _tm_train_block_q(S: int) -> int:
    # S>=3 drops to 256-row blocks (the VMEM measurement above), still
    # capped by _TM_TRAIN_BLOCK_Q; S<=2 takes _TM_TRAIN_BLOCK_Q
    # directly. The limit-dependent choice lives in ONE place and cannot
    # drift from a future _TM_VMEM_LIMIT edit (ADVICE r5 finding 3).
    return min(_TM_TRAIN_BLOCK_Q, 256) if S >= 3 else _TM_TRAIN_BLOCK_Q


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash_tm(qs, ks, v, coeffs, blocks, interpret):
    H = coeffs.shape[0] // qs[0].shape[0]
    out, _, _ = _tm_fwd_call(
        qs, ks, v, coeffs,
        H=H, block_q=blocks[0], save_residuals=False, interpret=interpret,
    )
    return out


def _flash_tm_fwd(qs, ks, v, coeffs, blocks, interpret):
    H = coeffs.shape[0] // qs[0].shape[0]
    out, o_all, lse = _tm_fwd_call(
        qs, ks, v, coeffs,
        H=H, block_q=blocks[2], save_residuals=True, interpret=interpret,
    )
    return out, (qs, ks, v, coeffs, o_all, lse)


def _flash_tm_bwd(blocks, interpret, res, g):
    qs, ks, v, coeffs, o_all, lse = res
    B, H, S, T, dv = o_all.shape
    g32 = g.astype(jnp.float32).reshape(B, T, H, dv)
    # base[b,t,h,s] = <g_t, O_s,t>; delta_s = c_s * base; dcoeffs = sum_t
    # (see _flash_bwd — identical residual algebra, token-major g and a
    # flat (B, T, H*S) stat layout matching lse, so the kernel reads
    # per-(head, stream) columns without a transpose)
    base = jnp.einsum("bthd,bhstd->bths", g32, o_all.astype(jnp.float32))
    dcoeffs = base.sum(1).reshape(B * H, S)
    delta = (
        base * coeffs.astype(jnp.float32).reshape(B, 1, H, S)
    ).reshape(B, T, H * S)
    dqs, dks, dv_grad = _tm_bwd_call(
        qs, ks, v, g.astype(qs[0].dtype), lse, delta, coeffs,
        H=H, interpret=interpret,
    )
    return dqs, dks, dv_grad, dcoeffs.astype(coeffs.dtype)


_flash_tm.defvjp(_flash_tm_fwd, _flash_tm_bwd)


def multi_stream_flash_attention_tm(
    qs, ks, v: jnp.ndarray, coeffs: jnp.ndarray, B: int, H: int,
    *,
    block_q: Optional[int] = None,
    block_q_train: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Token-major entry: ``qs``/``ks`` are tuples of S ``(B, T, H, d)``
    arrays (each the RESHAPED output of its own projection matmul — no
    transpose anywhere), ``v`` is ``(B, T, H, dv)``; returns
    ``(B, T, H, dv)``. The kernels run on the flat ``(B, T, H*width)``
    forms (all reshapes here are free row-major bitcasts). Callers must
    check :func:`use_tm` first; ineligible configs belong on
    :func:`multi_stream_flash_attention_bh`."""
    if interpret is None:
        interpret = _auto_interpret()
    S = len(qs)
    _, T, _, d = qs[0].shape
    dv = v.shape[-1]
    assert use_tm(S, T, 0.0), (
        f"tm kernels do not cover S={S}, T={T}; dispatch via use_tm"
    )
    dq, _, dqt, _ = default_blocks()
    # the S>=3 clamp is a hard VMEM envelope, applied uniformly: both
    # forward variants keep S full-T k/v arrays resident, and EXPLICIT
    # block picks are clamped the same as defaults (an un-clamped
    # explicit 512 at S=4 is exactly the measured 32.3 MB > 28 MB
    # Mosaic overflow the clamp exists to prevent)
    cap = _tm_train_block_q(S)
    blocks = (
        _pick_block(min(block_q if block_q is not None else dq, cap), T),
        0,
        _pick_block(min(block_q_train if block_q_train is not None else dqt,
                        cap), T),
        0,
    )
    c_r = jnp.broadcast_to(
        coeffs.astype(jnp.float32).T[None], (B, H, S)
    ).reshape(B * H, S)
    out = _flash_tm(
        tuple(q.reshape(B, T, H * d) for q in qs),
        tuple(k.reshape(B, T, H * d) for k in ks),
        v.reshape(B, T, H * dv),
        c_r, blocks, interpret,
    )
    return out.reshape(B, T, H, dv)


# ---------------------------------------------------------------------------
# Packed-projection tm variant: q/k/v ride as COLUMN WINDOWS of one
# (B, T, W) array — the raw output of a single fused projection matmul
# x @ [Wq1|..|WqS|Wk1|..|WkS|Wv]. pallas receives the same array once per
# logical operand with window-offset index maps (zero copies), and the
# backward emits ONE packed dproj in the same column order, which is
# exactly the operand the projection's own dx/dW matmuls need — no
# gradient concat materializes either. RoPE families cannot use this
# (rotating the q/k windows would need slice+concat copies); they stay on
# the per-array entry above.
# ---------------------------------------------------------------------------


def tm_packed_ok(S: int, H: int, d: int, dv: int) -> bool:
    """Shape eligibility for the packed tm kernels: the fused (B, T, W)
    projection is windowed with H*d- and H*dv-wide column blocks, so the
    V window offset 2*S*H*d must be a whole number of H*dv blocks (holds
    for every S when dv = 2d, and for S = 1, dv = d — only exotic dv/d
    ratios miss it), and both window widths must be 128-lane multiples —
    a BlockSpec block narrower than the array's last dim must divide
    into lanes (Mosaic lowering rule; narrow test-scale models miss it).
    Callers route ineligible shapes to the per-array tm path, whose
    blocks span each array's full last dim and are always legal."""
    Hd, Hdv = H * d, H * dv
    return (2 * S * Hd) % Hdv == 0 and Hd % 128 == 0 and Hdv % 128 == 0


def _tm_packed_specs(S, H, d, dv, T, block_q):
    """(in_specs for q_0..q_{S-1}, k_0.., v) over one packed (B, T, W)
    array, W = 2*S*H*d + H*dv. Asserts only the offset-alignment
    invariant (wrong windows = wrong math); the 128-lane width rule in
    tm_packed_ok is a TPU-lowering concern the DISPATCHER enforces —
    direct narrow-shape callers still work in interpret mode."""
    Hd, Hdv = H * d, H * dv
    assert (2 * S * Hd) % Hdv == 0, "packed v window misaligned"
    vcol = 2 * S * Hd // Hdv
    qspecs = [
        pl.BlockSpec(
            (None, block_q, Hd),
            (lambda s: lambda b, i: (b, i, s))(s),
            memory_space=pltpu.VMEM,
        )
        for s in range(S)
    ]
    kspecs = [
        pl.BlockSpec(
            (None, T, Hd),
            (lambda s: lambda b, i: (b, 0, S + s))(s),
            memory_space=pltpu.VMEM,
        )
        for s in range(S)
    ]
    vspec = pl.BlockSpec(
        (None, T, Hdv), lambda b, i: (b, 0, vcol), memory_space=pltpu.VMEM
    )
    return qspecs + kspecs + [vspec]


def _tm_fwd_call_packed(
    proj, coeffs, *, S, H, d, dv, block_q, save_residuals, interpret
):
    """Packed twin of :func:`_tm_fwd_call`: same kernel body, operands
    windowed out of ``proj`` (B, T, W)."""
    B, T, W = proj.shape
    BH = B * H
    block_q = _pick_block(block_q, T)
    nq = T // block_q

    in_specs = _tm_packed_specs(S, H, d, dv, T, block_q) + [
        pl.BlockSpec((block_q, T), lambda b, i: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((BH, S), lambda b, i: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    out_shapes = [jax.ShapeDtypeStruct((B, T, H * dv), proj.dtype)]
    out_specs = [
        pl.BlockSpec(
            (None, block_q, H * dv), lambda b, i: (b, i, 0),
            memory_space=pltpu.VMEM,
        ),
    ]
    if save_residuals:
        out_shapes += [
            jax.ShapeDtypeStruct((B, H, S, T, dv), proj.dtype),
            jax.ShapeDtypeStruct((B, T, H * S), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec(
                (None, H, S, block_q, dv),
                lambda b, i: (b, 0, 0, i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (None, block_q, H * S), lambda b, i: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
    results = pl.pallas_call(
        functools.partial(
            _tm_fwd_kernel, S=S, H=H, save_residuals=save_residuals
        ),
        grid=(B, nq),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
            vmem_limit_bytes=_TM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*([proj] * (2 * S + 1)), _tm_bias(T),
      coeffs.astype(jnp.float32))
    if save_residuals:
        return results
    return results[0], None, None


def _tm_bwd_kernel_packed(*refs, S: int, H: int):
    """Packed twin of :func:`_tm_bwd_kernel` (all streams; same shared
    math, :func:`_tm_bwd_columns`): the per-stream dq/dk and dv column
    groups store as ONE (T, W) ref in the packed projection order."""
    q_refs, refs = refs[:S], refs[S:]
    k_refs, refs = refs[:S], refs[S:]
    (v_ref, g_ref, lse_ref, delta_ref, c_ref, bias_ref, dproj_ref) = refs
    dq_cols, dk_cols, dv_cols = _tm_bwd_columns(
        q_refs, k_refs, v_ref, g_ref, lse_ref, delta_ref, c_ref,
        bias_ref[...].astype(jnp.float32),
        S=S, H=H, s_list=tuple(range(S)), out_dtype=dproj_ref.dtype,
    )
    cols = (
        [c for s_i in range(S) for c in dq_cols[s_i]]
        + [c for s_i in range(S) for c in dk_cols[s_i]]
        + dv_cols
    )
    dproj_ref[...] = jnp.concatenate(cols, axis=1)  # (T, W)


def _tm_bwd_call_packed(
    proj, g, lse, delta, coeffs, *, S, H, d, dv, interpret
):
    """Returns dproj (B, T, W) — the single packed gradient the fused
    projection matmul's own backward consumes directly."""
    B, T, W = proj.shape
    BH = B * H
    # packed windows with the whole-T 1-D grid index signature
    vspec = pl.BlockSpec(
        (None, T, H * dv), lambda b: (b, 0, 0), memory_space=pltpu.VMEM
    )
    stspec = pl.BlockSpec(
        (None, T, H * S), lambda b: (b, 0, 0), memory_space=pltpu.VMEM
    )
    Hd, Hdv = H * d, H * dv
    vcol = 2 * S * Hd // Hdv
    qspecs = [
        pl.BlockSpec(
            (None, T, Hd), (lambda s: lambda b: (b, 0, s))(s),
            memory_space=pltpu.VMEM,
        )
        for s in range(S)
    ]
    kspecs = [
        pl.BlockSpec(
            (None, T, Hd), (lambda s: lambda b: (b, 0, S + s))(s),
            memory_space=pltpu.VMEM,
        )
        for s in range(S)
    ]
    pvspec = pl.BlockSpec(
        (None, T, Hdv), lambda b: (b, 0, vcol), memory_space=pltpu.VMEM
    )
    results = pl.pallas_call(
        functools.partial(_tm_bwd_kernel_packed, S=S, H=H),
        grid=(B,),
        in_specs=qspecs + kspecs + [
            pvspec,
            vspec,
            stspec,
            stspec,
            pl.BlockSpec((BH, S), lambda b: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((T, T), lambda b: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((None, T, W), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((B, T, W), proj.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",),
            vmem_limit_bytes=_TM_VMEM_LIMIT,
        ),
        interpret=interpret,
    )(*([proj] * (2 * S + 1)), g, lse, delta,
      coeffs.astype(jnp.float32), _tm_bias(T))
    return results[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _flash_tm_packed(proj, coeffs, S, H, d, dv, blocks, interpret):
    out, _, _ = _tm_fwd_call_packed(
        proj, coeffs, S=S, H=H, d=d, dv=dv,
        block_q=blocks[0], save_residuals=False, interpret=interpret,
    )
    return out


def _flash_tm_packed_fwd(proj, coeffs, S, H, d, dv, blocks, interpret):
    out, o_all, lse = _tm_fwd_call_packed(
        proj, coeffs, S=S, H=H, d=d, dv=dv,
        block_q=blocks[2], save_residuals=True, interpret=interpret,
    )
    return out, (proj, coeffs, o_all, lse)


def _flash_tm_packed_bwd(S, H, d, dv, blocks, interpret, res, g):
    proj, coeffs, o_all, lse = res
    B, _, _, T, _ = o_all.shape
    g32 = g.astype(jnp.float32).reshape(B, T, H, dv)
    base = jnp.einsum("bthd,bhstd->bths", g32, o_all.astype(jnp.float32))
    dcoeffs = base.sum(1).reshape(B * H, S)
    delta = (
        base * coeffs.astype(jnp.float32).reshape(B, 1, H, S)
    ).reshape(B, T, H * S)
    dproj = _tm_bwd_call_packed(
        proj, g.astype(proj.dtype), lse, delta, coeffs,
        S=S, H=H, d=d, dv=dv, interpret=interpret,
    )
    return dproj, dcoeffs.astype(coeffs.dtype)


_flash_tm_packed.defvjp(_flash_tm_packed_fwd, _flash_tm_packed_bwd)


def multi_stream_flash_attention_tm_packed(
    proj: jnp.ndarray,  # (B, T, 2*S*H*d + H*dv) — [q_0..q_S|k_0..k_S|v]
    coeffs: jnp.ndarray,  # (S, H) float32
    B: int, H: int, S: int, d: int, dv: int,
    *,
    block_q: Optional[int] = None,
    block_q_train: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Packed-projection token-major entry (see the section comment):
    ``proj`` is the raw output of ONE fused projection matmul; returns
    (B, T, H, dv). No-RoPE families only; callers check use_tm."""
    if interpret is None:
        interpret = _auto_interpret()
    T = proj.shape[1]
    assert use_tm(S, T, 0.0), (
        f"tm kernels do not cover S={S}, T={T}; dispatch via use_tm"
    )
    dq, _, dqt, _ = default_blocks()
    # the S>=3 clamp is a hard VMEM envelope, applied uniformly: both
    # forward variants keep S full-T k/v arrays resident, and EXPLICIT
    # block picks are clamped the same as defaults (an un-clamped
    # explicit 512 at S=4 is exactly the measured 32.3 MB > 28 MB
    # Mosaic overflow the clamp exists to prevent)
    cap = _tm_train_block_q(S)
    blocks = (
        _pick_block(min(block_q if block_q is not None else dq, cap), T),
        0,
        _pick_block(min(block_q_train if block_q_train is not None else dqt,
                        cap), T),
        0,
    )
    c_r = jnp.broadcast_to(
        coeffs.astype(jnp.float32).T[None], (B, H, S)
    ).reshape(B * H, S)
    out = _flash_tm_packed(proj, c_r, S, H, d, dv, blocks, interpret)
    return out.reshape(B, T, H, dv)


def flash_vanilla_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, **kw
) -> jnp.ndarray:
    """Fused drop-in for ops.attention.vanilla_attention (causal, no
    dropout). q/k/v: (B, T, H, d)."""
    return multi_stream_flash_attention(
        q[None], k[None], v, vanilla_coeffs(q.shape[2]), **kw
    )


def flash_diff_attention(
    q1: jnp.ndarray,
    k1: jnp.ndarray,
    q2: jnp.ndarray,
    k2: jnp.ndarray,
    v: jnp.ndarray,
    lam: jnp.ndarray,
    **kw,
) -> jnp.ndarray:
    """Fused drop-in for ops.attention.diff_attention:
    ``att1 - lam*att2`` (diff_transformer.py:70) as coeffs [1, -lam]."""
    qs = jnp.stack([q1, q2])
    ks = jnp.stack([k1, k2])
    return multi_stream_flash_attention(qs, ks, v, diff_coeffs(lam), **kw)


def flash_ndiff_attention(
    qs: jnp.ndarray,
    ks: jnp.ndarray,
    v: jnp.ndarray,
    lams: jnp.ndarray,
    signs: jnp.ndarray,
    **kw,
) -> jnp.ndarray:
    """Fused drop-in for ops.attention.ndiff_attention: coeffs are
    ``sign_s * lambda_{s,h}`` (Ndiff_transformer.py:119-123 — the first
    map is scaled by lambda_0, not 1)."""
    return multi_stream_flash_attention(qs, ks, v, ndiff_coeffs(lams, signs), **kw)
