"""Inverted dropout, shared by attention-probability dropout
(control.py:59, diff_transformer.py:66-67) and residual/FFN dropout
(control.py:77,103). Identity at rate 0 (the reference default,
train.py:64) or without an rng (deterministic/eval mode)."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def dropout(x: jnp.ndarray, rate: float, rng: Optional[jax.Array]) -> jnp.ndarray:
    if rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)
