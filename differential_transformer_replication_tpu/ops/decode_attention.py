"""Fused single-query decode attention over the serving slot pool, with
optional int8 KV storage — the decode-side counterpart of ops/flash.py.

Serving decode is one token per step per slot: the engine's hot loop
(serving/engine.py) runs L=1 attention over every slot's ring KV cache.
As plain XLA ops (models/decode.py:``_attn_chunk``) that materializes the
per-stream fp32 score/softmax maps ``(S, B, H, M)`` in HBM every layer of
every step, and on TPU the decode step is bandwidth-bound: the K/V cache
stream dominates, so the score-map round-trips bound both inter-token
latency and how many concurrent slots fit at equal HBM.

This module is the fused alternative:

- :func:`decode_attention` — a Pallas kernel, grid ``(B*H, nk)``, that
  streams each slot row's ring cache tile-by-tile, runs the S per-stream
  softmaxes ONLINE (flash-style running max/sum carried in VMEM scratch),
  applies the lambda-weighted combine coefficients
  (models/decode.py:``_layer_coeffs`` — control S=1, diff S=2, ndiff S=N)
  in-kernel, and writes only the ``(B, H, dv)`` output. Per-stream
  attention maps and fp32 scores never reach HBM.
- int8 KV: :func:`quantize_kv` stores K/V rows as int8 with one fp32
  scale per (stream,) slot/head/token vector; the kernel dequantizes
  INSIDE the tile loads, so the HBM stream is genuinely half the bf16
  bytes (plus a ~4/d scale overhead). :func:`dequantize_kv` is the XLA
  twin used by the un-fused path and the parity oracles.
- :func:`decode_attention_reference` — the plain-XLA twin (same masking
  and fp32 softmax), used when ``decode_attention_impl == "xla"`` and by
  tests/tools/decode_attn_sweep.py as the parity baseline.
- :func:`quantize_params_int8` — the weight-side satellite: per-channel
  symmetric int8 quantize + dequantize of every matmul weight for
  ``load_params_for_inference(..., quantize="int8")``.

Ring-mask note: a decode row at absolute position ``pos`` over a ring of
``M = block_size`` slots sees slot ``m`` iff the position it holds is
non-negative, which reduces to ``m <= pos`` (for ``pos >= M`` every slot
holds a live key) — the same arithmetic ``_attn_chunk`` derives for its
general chunk case, collapsed for L=1 (see models/decode.py).

Kernel naming: the kernel body is ``_dattn_fwd_kernel`` so XLA op names
carry the ``_dattn_`` needle tools/profile_step.py buckets on.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from differential_transformer_replication_tpu.utils.compat import (
    CompilerParams as _CompilerParams,
)

from differential_transformer_replication_tpu.ops.flash import (
    auto_interpret,
    pick_block,
)
from differential_transformer_replication_tpu.ops.streams import NEG_INF

# K tile length streamed per grid step; clipped to a divisor of the cache
# length (pick_block). 512 keeps the int8 tile above the (32, 128) int8
# tiling floor and the VMEM footprint at O(S * block * d) per program.
_DEFAULT_BLOCK_K = 512


# ---------------------------------------------------------------------------
# int8 KV quantization (per-vector symmetric scales)
# ---------------------------------------------------------------------------


def quantize_kv(x: jnp.ndarray):
    """Symmetric int8 quantization over the LAST axis.

    One fp32 scale per leading-index vector (for a K row that is per
    (stream, slot, head, token) — the "per-head scale" granularity), so
    ``|dequant(q) - x| <= scale / 2`` elementwise. Returns
    ``(int8 values, fp32 scales)`` with ``scales.shape == x.shape[:-1]``.
    All-zero vectors get a tiny floor scale instead of a 0/0 NaN.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.round(xf / scale[..., None]).astype(jnp.int8)
    return q, scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """XLA-side inverse of :func:`quantize_kv` (the fused kernel performs
    the same multiply inside its tile loads instead)."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)).astype(dtype)


# ---------------------------------------------------------------------------
# The fused kernel
# ---------------------------------------------------------------------------


def _dattn_fwd_kernel(
    q_ref,  # (1, S, d) this row's per-stream queries (post-RoPE)
    k_ref,  # (S, 1, block_k, d) stored dtype (float) or int8
    v_ref,  # (1, block_k, dv)
    pos_ref,  # (1, BH) int32 SMEM: absolute position per (b, h) program
    c_ref,  # (S, H) float32 SMEM combine coefficients (_layer_coeffs)
    *refs,  # [k_scale_ref (S, 1, block_k), v_scale_ref (1, block_k) if
    #          quantized] then out_ref (1, dv) and scratch:
    #          m (S, 1), l (S, 1), acc (S, dv) — all fp32
    n_heads: int,
    quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, out_ref, m_scr, l_scr, acc_scr = refs
    else:
        out_ref, m_scr, l_scr, acc_scr = refs
    S, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[2]
    bh = pl.program_id(0)  # read at top level (interpreter cannot lower
    j = pl.program_id(1)   # program_id inside when-bodies; see ops/flash.py)
    nk = pl.num_programs(1)
    pos = pos_ref[0, bh]
    scale = 1.0 / math.sqrt(d)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # ring visibility collapses to col <= pos for a single decode row
    # (module docstring); a tile entirely past pos is skipped outright
    @pl.when(j * block_k <= pos)
    def _():
        q = q_ref[0]  # (S, d)
        k_j = k_ref[:, 0]  # (S, block_k, d)
        v_j = v_ref[0]  # (block_k, dv)
        if quantized:
            # dequant fused into the tile load: HBM carried int8 + one
            # fp32 scale per row vector; VMEM sees compute-dtype tiles
            k_j = (
                k_j.astype(jnp.float32) * ks_ref[:, 0][:, :, None]
            ).astype(q.dtype)
            v_j = (
                v_j.astype(jnp.float32) * vs_ref[0][:, None]
            ).astype(q.dtype)
        s = jax.lax.dot_general(
            q, k_j,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale  # (S, block_k)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        s = jnp.where(cols <= pos, s, NEG_INF)
        m_prev = m_scr[:]  # (S, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # (S, block_k)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_j.dtype), v_j,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (S, dv)
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new

    @pl.when(j == nk - 1)
    def _():
        # l >= 1 always (slot pos is visible to its own query); the floor
        # only guards never-stepped degenerate rows
        l_safe = jnp.maximum(l_scr[:], 1e-30)
        o_s = acc_scr[:] / l_safe  # (S, dv) per-stream outputs
        h = jax.lax.rem(bh, jnp.int32(n_heads))
        combined = o_s[0:1] * c_ref[0, h]
        for s_i in range(1, S):
            combined += o_s[s_i:s_i + 1] * c_ref[s_i, h]
        out_ref[:] = combined.astype(out_ref.dtype)


def decode_attention(
    qs: jnp.ndarray,  # (S, B, H, d) current-token queries (post-RoPE)
    k_cache: jnp.ndarray,  # (S, B, H, M, d) stored dtype or int8
    v_cache: jnp.ndarray,  # (B, H, M, dv)
    pos,  # (B,) int32 absolute position of each row's current token
    coeffs: jnp.ndarray,  # (S, H) float32 combine coefficients
    *,
    k_scale: Optional[jnp.ndarray] = None,  # (S, B, H, M) fp32 (int8 path)
    v_scale: Optional[jnp.ndarray] = None,  # (B, H, M) fp32
    block_k: int = 0,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused single-query multi-stream attention over the slot pool.

    The cache rides in the kernel-native pool layout (models/decode.py
    ``init_cache``): head-major, so the per-(b, h) ``(M, d)`` ring is
    contiguous and the grid flattens to ``B*H`` programs with zero-copy
    reshapes. The current token's K/V must already be written into the
    cache at ``pos % M`` (the same update-then-attend order
    ``_attn_chunk`` uses). Returns ``(B, H, dv)`` in the query dtype.
    """
    S, B, H, M, d = k_cache.shape
    dv = v_cache.shape[-1]
    BH = B * H
    if interpret is None:
        interpret = auto_interpret()
    bk = pick_block(block_k or _DEFAULT_BLOCK_K, M)
    nk = M // bk
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")

    q = qs.transpose(1, 2, 0, 3).reshape(BH, S, d)  # tiny: one token/row
    k = k_cache.reshape(S, BH, M, d)  # zero-copy: head-major layout
    v = v_cache.reshape(BH, M, dv)
    pos_bh = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32)[:, None], (B, H)
    ).reshape(1, BH)

    inputs = [q, k, v, pos_bh, coeffs.astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((1, S, d), lambda bh, j: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((S, 1, bk, d), lambda bh, j: (0, bh, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, dv), lambda bh, j: (bh, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, BH), lambda bh, j: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((S, H), lambda bh, j: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    if quantized:
        inputs += [
            k_scale.reshape(S, BH, M).astype(jnp.float32),
            v_scale.reshape(BH, M).astype(jnp.float32),
        ]
        in_specs += [
            pl.BlockSpec((S, 1, bk), lambda bh, j: (0, bh, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk), lambda bh, j: (bh, j),
                         memory_space=pltpu.VMEM),
        ]
    out = pl.pallas_call(
        functools.partial(
            _dattn_fwd_kernel, n_heads=H, quantized=quantized
        ),
        grid=(BH, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, dv), lambda bh, j: (bh, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, dv), qs.dtype),
        scratch_shapes=[
            pltpu.VMEM((S, 1), jnp.float32),
            pltpu.VMEM((S, 1), jnp.float32),
            pltpu.VMEM((S, dv), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*inputs)
    return out.reshape(B, H, dv)


def _dattn_paged_kernel(pt_ref, *args, n_heads: int, quantized: bool):
    """Paged twin of :func:`_dattn_fwd_kernel`: identical math — the
    page table did its work in the BlockSpec index maps (scalar
    prefetch resolved which physical page each grid step streams), so
    the kernel body sees the same (S, 1, page_size, d) tiles in
    LOGICAL ring order and delegates wholesale. Keeping the ``_dattn_``
    needle in the name preserves tools/profile_step.py's bucketing."""
    del pt_ref  # consumed by the index maps
    _dattn_fwd_kernel(*args, n_heads=n_heads, quantized=quantized)


def decode_attention_paged(
    qs: jnp.ndarray,  # (S, B, H, d) current-token queries (post-RoPE)
    k_pages: jnp.ndarray,  # (S, P, H, ps, d) stored dtype or int8
    v_pages: jnp.ndarray,  # (P, H, ps, dv)
    page_tables: jnp.ndarray,  # (B, pages_per_slot) int32
    pos,  # (B,) int32 absolute position of each row's current token
    coeffs: jnp.ndarray,  # (S, H) float32 combine coefficients
    *,
    k_scale: Optional[jnp.ndarray] = None,  # (S, P, H, ps) fp32
    v_scale: Optional[jnp.ndarray] = None,  # (P, H, ps) fp32
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused single-query decode attention THROUGH a page table.

    Same online-softmax multi-stream kernel as :func:`decode_attention`
    with one change: the KV tiles are loaded page-indexed. The page
    table rides as a SCALAR-PREFETCH operand
    (``pltpu.PrefetchScalarGridSpec``), so each K/V BlockSpec index map
    resolves grid step ``(bh, j)`` — row ``b = bh // H``, logical page
    ``j`` — to physical tile ``page_tables[b, j] * H + h`` of the
    head-major page pool (models/decode.py:``init_cache_paged``; the
    per-(page, head) ``(ps, d)`` tile is contiguous, so the reshape to
    ``(S, P*H, ps, d)`` is zero-copy). The tile length IS the page
    size: one grid step streams one page, int8 dequantization stays
    fused in the load. Because the table is a runtime int32 array,
    allocating/freeing/sharing/forking pages between calls compiles
    NOTHING new — the zero-recompile pin the serving engine keeps.

    Hardware note: Mosaic wants the (ps, d) tile at or above the dtype
    tiling floor — page sizes of 128+ (bf16) / 256+ (int8) keep the
    loads aligned on real TPUs; CPU interpret mode (tests) takes any
    divisor of block_size.
    """
    S, P, H, ps, d = k_pages.shape
    dv = v_pages.shape[-1]
    B, pp = page_tables.shape
    BH = B * H
    if interpret is None:
        interpret = auto_interpret()
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")

    q = qs.transpose(1, 2, 0, 3).reshape(BH, S, d)
    k = k_pages.reshape(S, P * H, ps, d)  # zero-copy: head-major pages
    v = v_pages.reshape(P * H, ps, dv)
    pos_bh = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32)[:, None], (B, H)
    ).reshape(1, BH)
    pt = jnp.asarray(page_tables, jnp.int32)

    def _k_map(bh, j, pt_ref):
        return (0, pt_ref[bh // H, j] * H + bh % H, 0, 0)

    def _v_map(bh, j, pt_ref):
        return (pt_ref[bh // H, j] * H + bh % H, 0, 0)

    inputs = [q, k, v, pos_bh, coeffs.astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((1, S, d), lambda bh, j, pt_ref: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((S, 1, ps, d), _k_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, ps, dv), _v_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, BH), lambda bh, j, pt_ref: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((S, H), lambda bh, j, pt_ref: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    if quantized:
        inputs += [
            k_scale.reshape(S, P * H, ps).astype(jnp.float32),
            v_scale.reshape(P * H, ps).astype(jnp.float32),
        ]
        in_specs += [
            pl.BlockSpec(
                (S, 1, ps),
                lambda bh, j, pt_ref: (0, pt_ref[bh // H, j] * H
                                       + bh % H, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps),
                lambda bh, j, pt_ref: (pt_ref[bh // H, j] * H
                                       + bh % H, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, pp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, dv), lambda bh, j, pt_ref: (bh, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((S, 1), jnp.float32),
            pltpu.VMEM((S, 1), jnp.float32),
            pltpu.VMEM((S, dv), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _dattn_paged_kernel, n_heads=H, quantized=quantized
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((BH, dv), qs.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(pt, *inputs)
    return out.reshape(B, H, dv)


def decode_attention_reference(
    qs: jnp.ndarray,  # (S, B, H, d)
    k_cache: jnp.ndarray,  # (S, B, H, M, d) FLOAT (dequantize first)
    v_cache: jnp.ndarray,  # (B, H, M, dv)
    pos,  # (B,) int32
    coeffs: jnp.ndarray,  # (S, H) float32
) -> jnp.ndarray:
    """Plain-XLA twin of :func:`decode_attention`: identical masking and
    fp32 per-stream softmax, materialized maps — the un-fused baseline
    (``decode_attention_impl == "xla"``) and the sweep/test oracle."""
    S, B, H, M, d = k_cache.shape
    scale = 1.0 / math.sqrt(d)
    scores = (
        jnp.einsum("sbhd,sbhmd->sbhm", qs, k_cache).astype(jnp.float32)
        * scale
    )
    visible = jnp.arange(M)[None, :] <= jnp.asarray(pos, jnp.int32)[:, None]
    scores = jnp.where(visible[None, :, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    combined = jnp.einsum("sh,sbhm->bhm", coeffs.astype(jnp.float32), probs)
    return jnp.einsum("bhm,bhme->bhe", combined.astype(v_cache.dtype), v_cache)


# ---------------------------------------------------------------------------
# Multi-query (L <= k rows per slot) variant — the speculative-decoding
# verify kernel (serving/spec.py). Each of a slot's L rows carries its
# own query token at its own absolute position (the last emitted token
# plus the k draft tokens); every row streams the SAME ring cache (or
# the same page-table-resolved pages) with ROW-CAUSAL visibility
# ``col <= pos[b, l]``, so row l sees the K/V rows 0..l wrote this very
# step (update-then-attend order, positions pos..pos+l) and nothing a
# later row wrote. L = 1 reduces to the single-query kernel above; the
# hot L=1 path keeps its dedicated kernel untouched.
# ---------------------------------------------------------------------------


def _dattn_mq_fwd_kernel(
    q_ref,  # (1, S * L, d) this slot's per-(stream, row) queries
    k_ref,  # (S, 1, block_k, d) stored dtype (float) or int8
    v_ref,  # (1, block_k, dv)
    pos_ref,  # (BH, L) int32 SMEM: absolute position per (b, h) row
    c_ref,  # (S, H) float32 SMEM combine coefficients (_layer_coeffs)
    *refs,  # [k_scale_ref (S, 1, block_k), v_scale_ref (1, block_k) if
    #          quantized] then out_ref (1, L, dv) and scratch:
    #          m (S, L), l (S, L), acc (S, L, dv) — all fp32
    n_heads: int,
    n_rows: int,
    quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, out_ref, m_scr, l_scr, acc_scr = refs
    else:
        out_ref, m_scr, l_scr, acc_scr = refs
    L = n_rows
    S, d = q_ref.shape[1] // L, q_ref.shape[2]
    block_k = k_ref.shape[2]
    bh = pl.program_id(0)
    j = pl.program_id(1)
    nk = pl.num_programs(1)
    # per-row positions; the tile-skip bound is the rows' max (static
    # unroll over the tiny L to keep SMEM reads scalar-indexed)
    pos_l = [pos_ref[bh, l] for l in range(L)]
    pos_max = pos_l[0]
    for l in range(1, L):
        pos_max = jnp.maximum(pos_max, pos_l[l])
    scale = 1.0 / math.sqrt(d)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # a tile entirely past every row's position is skipped outright;
    # per-row visibility (col <= pos[l]) is masked below. The row loop
    # is a STATIC unroll (L is tiny) running per row EXACTLY the op
    # sequence of the single-query kernel above — a batched (S, L,
    # block_k) dot would reassociate the d-reduction and break the
    # bit-parity the greedy spec/non-spec pin depends on.
    @pl.when(j * block_k <= pos_max)
    def _():
        q_all = q_ref[0].reshape(S, L, d)
        k_j = k_ref[:, 0]  # (S, block_k, d)
        v_j = v_ref[0]  # (block_k, dv)
        if quantized:
            k_j = (
                k_j.astype(jnp.float32) * ks_ref[:, 0][:, :, None]
            ).astype(q_all.dtype)
            v_j = (
                v_j.astype(jnp.float32) * vs_ref[0][:, None]
            ).astype(q_all.dtype)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1
        )
        for l in range(L):
            q = q_all[:, l]  # (S, d)
            s = jax.lax.dot_general(
                q, k_j,
                dimension_numbers=(((1,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            ) * scale  # (S, block_k)
            s = jnp.where(cols <= pos_l[l], s, NEG_INF)
            m_prev = m_scr[:, l:l + 1]  # (S, 1)
            m_new = jnp.maximum(
                m_prev, jnp.max(s, axis=-1, keepdims=True)
            )
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)  # (S, block_k)
            l_scr[:, l:l + 1] = (
                l_scr[:, l:l + 1] * alpha
                + jnp.sum(p, axis=-1, keepdims=True)
            )
            pv = jax.lax.dot_general(
                p.astype(v_j.dtype), v_j,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (S, dv)
            acc_scr[:, l] = acc_scr[:, l] * alpha + pv
            m_scr[:, l:l + 1] = m_new

    @pl.when(j == nk - 1)
    def _():
        h = jax.lax.rem(bh, jnp.int32(n_heads))
        for l in range(L):
            l_safe = jnp.maximum(l_scr[:, l:l + 1], 1e-30)
            o_s = acc_scr[:, l] / l_safe  # (S, dv) per-stream outputs
            combined = o_s[0:1] * c_ref[0, h]
            for s_i in range(1, S):
                combined += o_s[s_i:s_i + 1] * c_ref[s_i, h]
            out_ref[0, l] = combined[0].astype(out_ref.dtype)


def decode_attention_multi(
    qs: jnp.ndarray,  # (S, B, L, H, d) per-row queries (post-RoPE)
    k_cache: jnp.ndarray,  # (S, R, H, M, d) stored dtype or int8; R >= B
    v_cache: jnp.ndarray,  # (R, H, M, dv)
    pos,  # (B, L) int32 absolute position of each row's token
    coeffs: jnp.ndarray,  # (S, H) float32 combine coefficients
    *,
    k_scale: Optional[jnp.ndarray] = None,  # (S, R, H, M) fp32 (int8)
    v_scale: Optional[jnp.ndarray] = None,  # (R, H, M) fp32
    block_k: int = 0,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused multi-query decode attention over the slot pool: the
    speculative verify step's kernel. Row (b, l) attends slot b's ring
    cache with visibility ``col <= pos[b, l]`` — row-causal over the
    K/V rows this very step wrote (update-then-attend, positions
    pos..pos+L-1 written before any row attends). The cache may carry
    MORE batch rows than there are query slots (``R > B``: the spec
    engine's trash row rides at index B and is never attended).
    Returns ``(B, L, H, dv)`` in the query dtype."""
    S, B, L, H, d = qs.shape
    R, M = k_cache.shape[1], k_cache.shape[3]
    dv = v_cache.shape[-1]
    BH = B * H
    if interpret is None:
        interpret = auto_interpret()
    bk = pick_block(block_k or _DEFAULT_BLOCK_K, M)
    nk = M // bk
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")

    # (S, B, L, H, d) -> (B, H, S, L, d) -> (BH, S*L, d): stream-major
    # row packing, so the kernel's reshape to (S, L, d) is zero-copy
    q = qs.transpose(1, 3, 0, 2, 4).reshape(BH, S * L, d)
    k = k_cache.reshape(S, R * H, M, d)  # zero-copy: head-major layout
    v = v_cache.reshape(R * H, M, dv)
    pos_bh = jnp.repeat(
        jnp.asarray(pos, jnp.int32), H, axis=0
    )  # (B*H, L): row b*H+h carries slot b's positions

    inputs = [q, k, v, pos_bh, coeffs.astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((1, S * L, d), lambda bh, j: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((S, 1, bk, d), lambda bh, j: (0, bh, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk, dv), lambda bh, j: (bh, j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((BH, L), lambda bh, j: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((S, H), lambda bh, j: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    if quantized:
        inputs += [
            k_scale.reshape(S, R * H, M).astype(jnp.float32),
            v_scale.reshape(R * H, M).astype(jnp.float32),
        ]
        in_specs += [
            pl.BlockSpec((S, 1, bk), lambda bh, j: (0, bh, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk), lambda bh, j: (bh, j),
                         memory_space=pltpu.VMEM),
        ]
    out = pl.pallas_call(
        functools.partial(
            _dattn_mq_fwd_kernel, n_heads=H, n_rows=L,
            quantized=quantized,
        ),
        grid=(BH, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, L, dv), lambda bh, j: (bh, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, L, dv), qs.dtype),
        scratch_shapes=[
            pltpu.VMEM((S, L), jnp.float32),
            pltpu.VMEM((S, L), jnp.float32),
            pltpu.VMEM((S, L, dv), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*inputs)
    # (BH, L, dv) -> (B, L, H, dv)
    return out.reshape(B, H, L, dv).transpose(0, 2, 1, 3)


def _dattn_mq_paged_kernel(pt_ref, *args, n_heads: int, n_rows: int,
                           quantized: bool):
    """Paged twin of :func:`_dattn_mq_fwd_kernel`: the page table did
    its work in the scalar-prefetch index maps (same maps as
    :func:`decode_attention_paged`), so the body sees (S, 1, ps, d)
    tiles in logical ring order and delegates wholesale. ``_dattn_``
    needle kept for tools/profile_step.py bucketing."""
    del pt_ref  # consumed by the index maps
    _dattn_mq_fwd_kernel(*args, n_heads=n_heads, n_rows=n_rows,
                         quantized=quantized)


def decode_attention_multi_paged(
    qs: jnp.ndarray,  # (S, B, L, H, d) per-row queries (post-RoPE)
    k_pages: jnp.ndarray,  # (S, P, H, ps, d) stored dtype or int8
    v_pages: jnp.ndarray,  # (P, H, ps, dv)
    page_tables: jnp.ndarray,  # (B, pages_per_slot) int32
    pos,  # (B, L) int32 absolute position per row
    coeffs: jnp.ndarray,  # (S, H) float32 combine coefficients
    *,
    k_scale: Optional[jnp.ndarray] = None,  # (S, P, H, ps) fp32
    v_scale: Optional[jnp.ndarray] = None,  # (P, H, ps) fp32
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Multi-query verify attention THROUGH a page table: each of the
    L rows attends the paged ring through the SAME scalar-prefetch
    page-table index maps as :func:`decode_attention_paged` (one grid
    step streams one physical page, int8 dequant fused in the load)
    with row-causal ``col <= pos[b, l]`` visibility. Runtime int32
    tables ⇒ page churn between calls compiles nothing new."""
    S, P, H, ps, d = k_pages.shape
    dv = v_pages.shape[-1]
    B, pp = page_tables.shape
    L = qs.shape[2]
    BH = B * H
    if interpret is None:
        interpret = auto_interpret()
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale must be given together")

    q = qs.transpose(1, 3, 0, 2, 4).reshape(BH, S * L, d)
    k = k_pages.reshape(S, P * H, ps, d)  # zero-copy: head-major pages
    v = v_pages.reshape(P * H, ps, dv)
    pos_bh = jnp.repeat(jnp.asarray(pos, jnp.int32), H, axis=0)
    pt = jnp.asarray(page_tables, jnp.int32)

    def _k_map(bh, j, pt_ref):
        return (0, pt_ref[bh // H, j] * H + bh % H, 0, 0)

    def _v_map(bh, j, pt_ref):
        return (pt_ref[bh // H, j] * H + bh % H, 0, 0)

    inputs = [q, k, v, pos_bh, coeffs.astype(jnp.float32)]
    in_specs = [
        pl.BlockSpec((1, S * L, d), lambda bh, j, pt_ref: (bh, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((S, 1, ps, d), _k_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, ps, dv), _v_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((BH, L), lambda bh, j, pt_ref: (0, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((S, H), lambda bh, j, pt_ref: (0, 0),
                     memory_space=pltpu.SMEM),
    ]
    if quantized:
        inputs += [
            k_scale.reshape(S, P * H, ps).astype(jnp.float32),
            v_scale.reshape(P * H, ps).astype(jnp.float32),
        ]
        in_specs += [
            pl.BlockSpec(
                (S, 1, ps),
                lambda bh, j, pt_ref: (0, pt_ref[bh // H, j] * H
                                       + bh % H, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, ps),
                lambda bh, j, pt_ref: (pt_ref[bh // H, j] * H
                                       + bh % H, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BH, pp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, L, dv),
                               lambda bh, j, pt_ref: (bh, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((S, L), jnp.float32),
            pltpu.VMEM((S, L), jnp.float32),
            pltpu.VMEM((S, L, dv), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _dattn_mq_paged_kernel, n_heads=H, n_rows=L,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((BH, L, dv), qs.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(pt, *inputs)
    return out.reshape(B, H, L, dv).transpose(0, 2, 1, 3)


def decode_attention_multi_reference(
    qs: jnp.ndarray,  # (S, B, L, H, d)
    k_cache: jnp.ndarray,  # (S, B, H, M, d) FLOAT (dequantize first)
    v_cache: jnp.ndarray,  # (B, H, M, dv)
    pos,  # (B, L) int32
    coeffs: jnp.ndarray,  # (S, H) float32
) -> jnp.ndarray:
    """Plain-XLA twin of :func:`decode_attention_multi`: a STATIC
    unroll over the tiny L, each row running EXACTLY
    :func:`decode_attention_reference`'s op sequence at its own
    position — a batched ``sbhlm`` einsum would reassociate the
    contractions and break the bit-parity the greedy spec/non-spec pin
    depends on. Returns ``(B, L, H, dv)``."""
    L = qs.shape[2]
    pos = jnp.asarray(pos, jnp.int32)
    rows = [
        decode_attention_reference(qs[:, :, l], k_cache, v_cache,
                                   pos[:, l], coeffs)
        for l in range(L)
    ]
    return jnp.stack(rows, axis=1)  # (B, L, H, dv)


# ---------------------------------------------------------------------------
# int8 weight quantization (load_params_for_inference satellite)
# ---------------------------------------------------------------------------

_QKV_KEYS = ("wq", "wk", "wv")


def quantize_weight_int8(w: jnp.ndarray, axis: int) -> jnp.ndarray:
    """Symmetric per-output-channel int8 quantize + dequantize of one
    matmul weight: one fp32 scale per slice along the CONTRACTION
    ``axis``, so every output channel keeps its own dynamic range.
    Returns the dequantized weight in the input dtype (the int8 form is
    transient — "dequant-on-load")."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.round(wf / scale).astype(jnp.int8)
    return (q.astype(jnp.float32) * scale).astype(w.dtype)


def quantize_params_int8(params: dict) -> dict:
    """Apply :func:`quantize_weight_int8` to every matmul weight in a
    model params tree: the attention projections (``wq``/``wk``/``wv``,
    contraction axis = the embedding axis) and every Linear ``w``
    (attention out-proj, FFN gate/xform/out, lm head; contraction axis
    0). Embeddings, norms, lambda vectors and biases pass through
    untouched — quantizing those buys nothing (tiny) and costs accuracy
    disproportionately."""

    def walk(node, name=None):
        if isinstance(node, dict):
            return {k: walk(v, k) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v, name) for v in node)
        if name in _QKV_KEYS:
            # (E, H, d) or stacked (S, E, H, d): E is always axis -3
            return quantize_weight_int8(node, axis=-3)
        if name == "w" and getattr(node, "ndim", 0) == 2:
            return quantize_weight_int8(node, axis=0)
        return node

    return walk(params)
