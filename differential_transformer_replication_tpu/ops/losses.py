"""Fused (chunked) linear + cross-entropy loss.

The reference computes the LM loss by materializing full logits and
calling ``F.cross_entropy`` on the flattened ``(B*T, V)`` tensor
(control.py:153-159, identical in the other families). The dense
equivalent here (models/common.py:cross_entropy_loss) does the same — at
long context that tensor IS the memory wall: (B, T, V) bf16 logits plus
an fp32 copy for the softmax, e.g. ~1.2 GB at T=16384, V=12000, B=1,
dwarfing every activation the flash kernels (ops/flash.py) were built to
avoid.

This op never materializes more than one chunk of logits. Forward scans
position-chunks of the pre-head activations, computing each chunk's
logits + log-softmax + target gather on the fly; the custom VJP
recomputes each chunk's logits in the backward (the same
trade-the-matmul-for-memory bargain as flash attention) and emits
``dlogits = softmax - onehot`` chunk-locally, accumulating the lm-head
weight/bias grads in fp32 carries. Peak extra memory is
O(chunk * V) instead of O(B * T * V).

Numerics match the dense path operation-for-operation: the chunk matmul
runs in the activations' compute dtype (bf16 on TPU), logits are then
upcast to fp32 for log-softmax (models/common.py:cross_entropy_loss), and
the mean is over all B*T positions. Chunking over positions cannot change
per-position values — softmax is position-local — so the only deviation
from dense is fp32 summation order.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _pad_chunks(h2: jnp.ndarray, t1: jnp.ndarray, chunk: int):
    """(N, E) activations + (N,) targets -> (C, chunk, ...) with a validity
    mask for the tail padding."""
    n = h2.shape[0]
    pad = (-n) % chunk
    mask = jnp.ones((n,), jnp.float32)
    if pad:
        h2 = jnp.pad(h2, ((0, pad), (0, 0)))
        t1 = jnp.pad(t1, ((0, pad),))
        mask = jnp.pad(mask, ((0, pad),))
    c = h2.shape[0] // chunk
    return (
        h2.reshape(c, chunk, -1),
        t1.reshape(c, chunk),
        mask.reshape(c, chunk),
    )


def _chunk_logp(hc, tc, w, b):
    """One chunk's fp32 (log-probs at targets, logits) — the dense path's
    op sequence: compute-dtype matmul, fp32 upcast, log_softmax, gather."""
    logits = hc @ w.astype(hc.dtype)
    if b is not None:
        logits = logits + b.astype(hc.dtype)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, tc[:, None], axis=-1)[:, 0]
    return ll, logits


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_linear_cross_entropy(
    h: jnp.ndarray,
    w: jnp.ndarray,
    b: Optional[jnp.ndarray],
    targets: jnp.ndarray,
    chunk: int = 2048,
) -> jnp.ndarray:
    """Mean cross-entropy of ``logits = h @ w (+ b)`` against ``targets``
    without materializing the full logits tensor.

    ``h``: (..., E) pre-head activations (compute dtype); ``w``: (E, V)
    fp32 lm-head weight; ``b``: (V,) bias or None; ``targets``: int (...)
    matching h's leading dims. ``chunk``: positions per scanned block.
    """
    h2 = h.reshape(-1, h.shape[-1])
    t1 = targets.reshape(-1)
    n = h2.shape[0]
    hc, tc, mc = _pad_chunks(h2, t1, chunk)

    def body(acc, xs):
        hcb, tcb, mcb = xs
        ll, _ = _chunk_logp(hcb, tcb, w, b)
        return acc + jnp.sum(ll * mcb), None

    loss_sum, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (hc, tc, mc))
    return -loss_sum / n


def _fwd(h, w, b, targets, chunk):
    return fused_linear_cross_entropy(h, w, b, targets, chunk), (h, w, b, targets)


def _bwd(chunk, res, g):
    h, w, b, targets = res
    h2 = h.reshape(-1, h.shape[-1])
    t1 = targets.reshape(-1)
    n = h2.shape[0]
    hc, tc, mc = _pad_chunks(h2, t1, chunk)
    # d(loss)/d(logits) per position: (softmax - onehot) * (-(-1)) * g / n;
    # loss = -sum(ll)/n so dlogits = (softmax - onehot) * g / n
    scale = (g / n).astype(jnp.float32)
    wc = w.astype(h.dtype)

    def body(carry, xs):
        dw_acc, db_acc = carry
        hcb, tcb, mcb = xs
        _, logits = _chunk_logp(hcb, tcb, w, b)
        probs = jax.nn.softmax(logits, axis=-1)
        dlog = probs.at[jnp.arange(tcb.shape[0]), tcb].add(-1.0)
        dlog = dlog * (mcb[:, None] * scale)
        # the dense path's cast structure: fp32 softmax-grad cast to the
        # compute dtype before the two matmuls, fp32 param-grad accumulate
        dlog_c = dlog.astype(h.dtype)
        dh_b = dlog_c @ wc.T
        dw_b = (hcb.T @ dlog_c).astype(jnp.float32)
        db_b = jnp.sum(dlog, axis=0)
        return (dw_acc + dw_b, db_acc + db_b), dh_b

    (dw, db), dh = jax.lax.scan(
        body,
        (jnp.zeros(w.shape, jnp.float32), jnp.zeros((w.shape[1],), jnp.float32)),
        (hc, tc, mc),
    )
    dh = dh.reshape(-1, h.shape[-1])[:n].reshape(h.shape)
    d_targets = jnp.zeros(targets.shape, jax.dtypes.float0)
    db_out = None if b is None else db.astype(b.dtype)
    return dh, dw.astype(w.dtype), db_out, d_targets


fused_linear_cross_entropy.defvjp(_fwd, _bwd)


@jax.custom_vjp
def dense_linear_cross_entropy(
    h: jnp.ndarray,
    w: jnp.ndarray,
    b: Optional[jnp.ndarray],
    targets: jnp.ndarray,
) -> jnp.ndarray:
    """Dense (non-chunked) fused lm-head + mean cross-entropy with a
    hand-written backward.

    Same math as ``apply_tail`` + ``cross_entropy_loss``
    (models/common.py; control.py:153-159), but the head's weight/input
    grads are computed INSIDE the VJP with explicit bf16-operand
    dot_generals (fp32 accumulation): left to autodiff, XLA fuses the
    softmax backward into an extra fp32 TRANSPOSED materialization of
    the (B*T, V) grad as the dW matmul operand — 786 MB of HBM traffic
    at the recipe scale, profiled ~1.8 ms/step on v5e. Residuals keep
    the forward logits (no recompute — the chunked op above makes the
    opposite trade for long context, where logits don't fit)."""
    loss, _, _ = _dense_primal(h, w, b, targets)
    return loss


def _dense_primal(h, w, b, targets):
    logits = h @ w.astype(h.dtype)
    if b is not None:
        logits = logits + b.astype(h.dtype)
    l32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(l32, axis=-1)
    # gather from the stored-dtype logits and upcast AFTER (identical
    # values — l32 is itself a convert of ``logits``): leaves the reduces
    # as l32's only consumers, so the convert fuses into them instead of
    # materializing a full fp32 (B, T, V) copy (786 MB at recipe scale;
    # profiled as a ~1.8 ms fusion output on v5e, round 4)
    tgt = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    )[..., 0].astype(jnp.float32)
    return jnp.mean(lse - tgt), logits, lse


def _dense_fwd(h, w, b, targets):
    loss, logits, lse = _dense_primal(h, w, b, targets)
    return loss, (h, w, b, logits, lse, targets)


def _dense_bwd(res, g):
    h, w, b, logits, lse, targets = res
    n = logits.size // logits.shape[-1]
    V = logits.shape[-1]
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    d32 = (p - (iota == targets[..., None]).astype(jnp.float32)) * (g / n)
    d = d32.astype(h.dtype)
    d2 = d.reshape(-1, d.shape[-1])  # (N, V)
    h2 = h.reshape(-1, h.shape[-1])  # (N, E)
    dw = jax.lax.dot_general(
        h2, d2,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(w.dtype)  # (E, V)
    dh = jax.lax.dot_general(
        d2, w.astype(h.dtype),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=h.dtype,
    ).reshape(h.shape)
    if b is None:
        db = None
    else:
        # sum_n d32[n, v] decomposed as (column-sums of p) - (target
        # counts): identical math (sum of p - onehot), but the reduce
        # fuses into the pass that produces ``d`` instead of forcing a
        # separate fp32 (N, V) materialization of d32 — profiled ~1.8
        # ms/step of pure HBM traffic at the recipe scale on v5e (r4)
        # targets must be in [0, V): scatter .add wraps NEGATIVE indices
        # (unlike the one-hot formulation, which ignored them), so a future
        # ignore-index sentinel (e.g. -1) would silently corrupt column V-1.
        # The mask makes such sentinels contribute nothing here; full
        # ignore-index support would also need masking in the fwd gather.
        t = targets.reshape(-1)
        counts = jnp.zeros((V,), jnp.float32).at[t].add(
            jnp.where(t >= 0, 1.0, 0.0)
        )
        colsum = jnp.sum(p, axis=tuple(range(p.ndim - 1)))
        db = ((colsum - counts) * (g / n)).astype(b.dtype)
    d_targets = jnp.zeros(targets.shape, jax.dtypes.float0)
    return dh, dw, db, d_targets


dense_linear_cross_entropy.defvjp(_dense_fwd, _dense_bwd)
