"""Fused residual-add + LayerNorm — the block-boundary Pallas kernel.

Every transformer block boundary in this codebase is the same three-op
sequence: ``x = x + delta`` (residual add), then ``layer_norm(x)`` for
the next consumer. Un-fused, XLA runs it as three HBM round-trips over
the (B, T, E) activation — write the sum, read it back for the fp32
statistics, read it again for the normalize/affine pass (round-4/5
profiles: the ``add``/``reduce``/``multiply`` families around the
attention and FFN entry points). This kernel does all of it in ONE pass:
each (block_m, E) tile is loaded once, the residual sum is written back
for the carry, and the normalized output is produced from the same
VMEM-resident tile.

Numerics are EXACTLY :func:`ops.norms.layer_norm`'s: the add happens in
the stored dtype (the residual stream's compute dtype, matching
``x + delta`` at the XLA level), statistics are computed in float32 with
BIASED variance and ``eps`` inside the square root
(diff_transformer.py:17-19), the affine runs in float32 against the
fp32 scale/bias params, and only the final result is cast back. The
full-width reduction lives inside one tile (the last axis is never
split), so there is no cross-tile statistics plumbing.

Backward is a custom VJP with a single Pallas kernel: the standard
LayerNorm backward (recomputing statistics from the saved post-add
activation — cheaper than saving (M, 1) stats tensors with lane-width-1
layouts), the residual passthrough cotangent added in the same pass, and
the scale/bias gradients accumulated across the row grid in fp32.

``group_layer_norm`` is a full-width LayerNorm in this codebase
(ops/norms.py parity note), so the Group aliases are the same kernels.

Exports (all differentiable, interpret-mode on CPU like ops/flash.py):
  - ``fused_add_norm(x, delta, w, b)   -> (x + delta, LN(x + delta))``
  - ``fused_norm(x, w, b)              -> LN(x)``
  - ``fused_add_group_norm`` / ``fused_group_norm`` — the GLN aliases.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from differential_transformer_replication_tpu.ops.flash import (
    auto_interpret,
    pick_block,
)
from differential_transformer_replication_tpu.utils.compat import (
    CompilerParams as _CompilerParams,
)

_DEFAULT_BLOCK_M = 256


def _stats(xf: jnp.ndarray, eps: float):
    """fp32 mean / xhat for one (block_m, E) tile — layer_norm's exact
    formula: biased variance, eps inside the sqrt, division (not rsqrt,
    which differs in the last ulp)."""
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    c = xf - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    denom = jnp.sqrt(var + eps)
    return c / denom, denom


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _addnorm_fwd_kernel(*refs, eps: float, has_delta: bool):
    if has_delta:
        x_ref, d_ref, w_ref, b_ref, outx_ref, outn_ref = refs
        x = x_ref[...] + d_ref[...]  # stored dtype, like the XLA add
        outx_ref[...] = x
    else:
        x_ref, w_ref, b_ref, outn_ref = refs
        x = x_ref[...]
    xhat, _ = _stats(x.astype(jnp.float32), eps)
    outn_ref[...] = (xhat * w_ref[...] + b_ref[...]).astype(outn_ref.dtype)


def _fwd_call(x2, d2, w2, b2, *, eps, has_delta, block_m, interpret):
    M, E = x2.shape
    bm = pick_block(block_m, M)
    grid = (M // bm,)
    row_spec = pl.BlockSpec((bm, E), lambda i: (i, 0), memory_space=pltpu.VMEM)
    par_spec = pl.BlockSpec((1, E), lambda i: (0, 0), memory_space=pltpu.VMEM)
    in_specs = [row_spec] + ([row_spec] if has_delta else []) + [par_spec, par_spec]
    out_shapes = [jax.ShapeDtypeStruct((M, E), x2.dtype)]
    out_specs = [row_spec]
    if has_delta:
        out_shapes = [jax.ShapeDtypeStruct((M, E), x2.dtype)] + out_shapes
        out_specs = [row_spec] + out_specs
    inputs = (x2, d2, w2, b2) if has_delta else (x2, w2, b2)
    return pl.pallas_call(
        functools.partial(_addnorm_fwd_kernel, eps=eps, has_delta=has_delta),
        grid=grid,
        in_specs=in_specs,
        out_shape=out_shapes,
        out_specs=out_specs,
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _addnorm_bwd_kernel(*refs, eps: float, has_gx: bool):
    """dx for one tile + fp32 dw/db partials accumulated across the grid.

    ``x_ref`` holds the POST-add activation (the forward's carry output),
    so statistics recompute is one VPU pass over the already-resident
    tile. With the residual carry cotangent ``gx`` present, the add's
    passthrough is summed in the same pass (d/dx and d/ddelta are the
    same array; the wrapper returns it for both).
    """
    if has_gx:
        x_ref, w_ref, gn_ref, gx_ref, dx_ref, dw_ref, db_ref = refs
    else:
        x_ref, w_ref, gn_ref, dx_ref, dw_ref, db_ref = refs
    i = pl.program_id(0)
    xhat, denom = _stats(x_ref[...].astype(jnp.float32), eps)
    gn = gn_ref[...].astype(jnp.float32)
    dxh = gn * w_ref[...]  # (bm, E) fp32
    m1 = jnp.mean(dxh, axis=-1, keepdims=True)
    m2 = jnp.mean(dxh * xhat, axis=-1, keepdims=True)
    dx = (dxh - m1 - xhat * m2) / denom
    if has_gx:
        dx = dx + gx_ref[...].astype(jnp.float32)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    pw = jnp.sum(gn * xhat, axis=0, keepdims=True)  # (1, E) fp32
    pb = jnp.sum(gn, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = pw
        db_ref[...] = pb

    @pl.when(i > 0)
    def _acc():
        dw_ref[...] += pw
        db_ref[...] += pb


def _bwd_call(x2, w2, gn2, gx2, *, eps, block_m, interpret):
    M, E = x2.shape
    has_gx = gx2 is not None
    bm = pick_block(block_m, M)
    grid = (M // bm,)
    row_spec = pl.BlockSpec((bm, E), lambda i: (i, 0), memory_space=pltpu.VMEM)
    par_spec = pl.BlockSpec((1, E), lambda i: (0, 0), memory_space=pltpu.VMEM)
    in_specs = [row_spec, par_spec, row_spec] + ([row_spec] if has_gx else [])
    inputs = (x2, w2, gn2) + ((gx2,) if has_gx else ())
    return pl.pallas_call(
        functools.partial(_addnorm_bwd_kernel, eps=eps, has_gx=has_gx),
        grid=grid,
        in_specs=in_specs,
        out_shape=[
            jax.ShapeDtypeStruct((M, E), x2.dtype),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
        ],
        out_specs=[row_spec, par_spec, par_spec],
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# custom_vjp wrappers (2D, (M, E)) — the public API reshapes
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _add_norm2(x2, d2, w2, b2, eps, block_m, interpret):
    return _fwd_call(
        x2, d2, w2, b2, eps=eps, has_delta=True, block_m=block_m,
        interpret=interpret,
    )


def _add_norm2_fwd(x2, d2, w2, b2, eps, block_m, interpret):
    xnew, normed = _add_norm2(x2, d2, w2, b2, eps, block_m, interpret)
    return (xnew, normed), (xnew, w2)


def _add_norm2_bwd(eps, block_m, interpret, res, ct):
    xnew, w2 = res
    gx, gn = ct
    dx, dw, db = _bwd_call(
        xnew, w2, gn, gx, eps=eps, block_m=block_m, interpret=interpret
    )
    # x and delta enter only through their sum: one cotangent serves both
    return dx, dx, dw, db


_add_norm2.defvjp(_add_norm2_fwd, _add_norm2_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _norm2(x2, w2, b2, eps, block_m, interpret):
    return _fwd_call(
        x2, None, w2, b2, eps=eps, has_delta=False, block_m=block_m,
        interpret=interpret,
    )[0]


def _norm2_fwd(x2, w2, b2, eps, block_m, interpret):
    return _norm2(x2, w2, b2, eps, block_m, interpret), (x2, w2)


def _norm2_bwd(eps, block_m, interpret, res, gn):
    x2, w2 = res
    dx, dw, db = _bwd_call(
        x2, w2, gn, None, eps=eps, block_m=block_m, interpret=interpret
    )
    return dx, dw, db


_norm2.defvjp(_norm2_fwd, _norm2_bwd)


def _flatten(x: jnp.ndarray):
    E = x.shape[-1]
    return x.reshape(-1, E), x.shape


def fused_add_norm(
    x: jnp.ndarray,
    delta: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    eps: float = 1e-5,
    *,
    block_m: int = _DEFAULT_BLOCK_M,
    interpret: Optional[bool] = None,
):
    """``(x + delta, layer_norm(x + delta, weight, bias))`` in one fused
    pass. ``x``/``delta``: (..., E) in the compute dtype; ``weight``/
    ``bias``: (E,) float32 (the LN params are never downcast, matching
    ops/norms.py). Differentiable via the fused backward kernel."""
    if interpret is None:
        interpret = auto_interpret()
    x2, shape = _flatten(x)
    d2, _ = _flatten(delta)
    w2 = weight.astype(jnp.float32).reshape(1, -1)
    b2 = bias.astype(jnp.float32).reshape(1, -1)
    xnew, normed = _add_norm2(x2, d2, w2, b2, float(eps), block_m, interpret)
    return xnew.reshape(shape), normed.reshape(shape)


def fused_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    bias: jnp.ndarray,
    eps: float = 1e-5,
    *,
    block_m: int = _DEFAULT_BLOCK_M,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Single-pass :func:`ops.norms.layer_norm` (no residual input)."""
    if interpret is None:
        interpret = auto_interpret()
    x2, shape = _flatten(x)
    w2 = weight.astype(jnp.float32).reshape(1, -1)
    b2 = bias.astype(jnp.float32).reshape(1, -1)
    return _norm2(x2, w2, b2, float(eps), block_m, interpret).reshape(shape)


# The reference's GroupLayerNorm IS a full-width LayerNorm (ops/norms.py
# parity note) — same kernels, alias kept so call sites document which
# reference module they replicate.
fused_add_group_norm = fused_add_norm
fused_group_norm = fused_norm
