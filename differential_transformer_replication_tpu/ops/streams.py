"""The multi-stream attention decomposition shared by every backend.

All three model families compute a linear combination of causal softmax
streams over one V:

    out = sum_s coeff[s, h] * softmax(Q_s K_s^T / sqrt(d)) @ V

  - control (control.py:52-62):             S=1, coeff [1]
  - diff    (diff_transformer.py:70):       S=2, coeff [1, -lambda]
  - ndiff   (Ndiff_transformer.py:119-123): S=n, coeff sign_s * lambda_{s,h}
    (the first map is scaled by lambda_0, NOT 1 — the documented semantic
    difference from the 2-term model)

Both fused backends — the Pallas flash kernel (ops/flash.py) and the
ring sequence-parallel path (parallel/ring.py) — consume these builders so
the per-family combine semantics live in exactly one place.
"""

from __future__ import annotations

import jax.numpy as jnp

# finite stand-in for -inf in masked-softmax accumulators: keeps
# exp(m - m_new) NaN-free when a row has seen only masked blocks
NEG_INF = -1e30


def vanilla_coeffs(n_head: int) -> jnp.ndarray:
    """(1, H) of ones: a single plain softmax stream."""
    return jnp.ones((1, n_head), jnp.float32)


def diff_coeffs(lam: jnp.ndarray) -> jnp.ndarray:
    """(2, H): att1 - lambda * att2 (diff_transformer.py:70)."""
    return jnp.stack([jnp.ones_like(lam), -lam]).astype(jnp.float32)


def ndiff_coeffs(lams: jnp.ndarray, signs: jnp.ndarray) -> jnp.ndarray:
    """(n, H): sign_s * lambda_{s,h} (Ndiff_transformer.py:119-123)."""
    return signs[:, None].astype(jnp.float32) * lams.astype(jnp.float32)
