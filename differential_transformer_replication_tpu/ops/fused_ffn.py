"""Fused SwiGLU FFN — the Pallas kernel for the block's MLP half.

The reference FFN (control.py:100-104, shared by all three families) is
``silu(x @ Wg + bg) * (x @ Wx + bx)`` behind a pre-LN. Un-fused, XLA
materializes BOTH (M, 4E) pre-activations to HBM, reads them back for
the silu/product pass, and writes the (M, 4E) hidden — at the recipe
scale (M = 16384 rows, 4E = 3072) that is ~500 MB of pure epilogue
traffic per layer per direction, the largest un-fused block in the
round-4/5 step decompositions (BASELINE.md). This kernel computes the
whole chain tile-by-tile: the gate and xform matmuls feed the MXU from
one VMEM-resident activation tile, the SiLU and elementwise product run
on the fp32 accumulators in registers, and only the final hidden tile
ever reaches HBM.

Grid layout is (hidden-tiles, row-tiles) with rows INNER so the weight
column blocks stay VMEM-resident across the whole row sweep — weights
stream exactly once per call instead of once per row tile.

One entry point: :func:`fused_swiglu` — gate/xform matmuls -> SiLU ->
product; the caller supplies an already-normalized activation (the
training blocks feed it from ops/fused_norm_residual.py's add+LN
kernel, which owns the pre-LN at every block boundary — a standalone
LN never precedes the FFN without a residual add in front, so there
is deliberately no LN-in-front variant here).

Backward is a custom VJP around ONE Pallas kernel that recomputes the
pre-activations tile-by-tile (flash-style: matmul recompute is cheaper
than an (M, 4E) x2 HBM round-trip of saved activations), produces the
gate/xform pre-activation cotangents, and accumulates the fp32 weight
and bias gradients in-kernel across the row grid. The two remaining
contractions (``dg @ Wg^T + dt @ Wx^T``) run as plain XLA ops on those
outputs — they are MXU-bound matmuls XLA already schedules well.

Interpret-mode fallback on CPU (like ops/flash.py), so the tier-1 CPU
suite exercises the real kernel code paths.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from differential_transformer_replication_tpu.ops.flash import (
    auto_interpret,
    pick_block,
)
from differential_transformer_replication_tpu.utils.compat import (
    CompilerParams as _CompilerParams,
)

_DEFAULT_BLOCK_M = 256
_DEFAULT_BLOCK_F = 512


def _pre_acts(xn, wg_ref, bg_ref, wx_ref, bx_ref):
    """(bm, bf) fp32 gate/xform pre-activations for one tile pair: the
    MXU contraction in the stored dtype with fp32 accumulation."""
    g = jax.lax.dot_general(
        xn, wg_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bg_ref[...].astype(jnp.float32)
    t = jax.lax.dot_general(
        xn, wx_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bx_ref[...].astype(jnp.float32)
    return g, t


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _ffn_fwd_kernel(*refs):
    x_ref, wg_ref, bg_ref, wx_ref, bx_ref, outh_ref = refs
    xn = x_ref[...]
    g, t = _pre_acts(xn, wg_ref, bg_ref, wx_ref, bx_ref)
    outh_ref[...] = (g * jax.nn.sigmoid(g) * t).astype(outh_ref.dtype)


def _specs(E, F, bm, bf):
    """(in_specs sans gh, shared index maps) for both kernels. Grid is
    (F//bf, M//bm) — j (hidden tile) OUTER, i (row tile) inner."""
    x_spec = pl.BlockSpec((bm, E), lambda j, i: (i, 0), memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((E, bf), lambda j, i: (0, j), memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((1, bf), lambda j, i: (0, j), memory_space=pltpu.VMEM)
    h_spec = pl.BlockSpec((bm, bf), lambda j, i: (i, j), memory_space=pltpu.VMEM)
    in_specs = [x_spec, w_spec, b_spec, w_spec, b_spec]
    return in_specs, x_spec, w_spec, b_spec, h_spec


def _fwd_call(x2, wg, bg2, wx, bx2, *, block_m, block_f, interpret):
    M, E = x2.shape
    F = wg.shape[1]
    bm = pick_block(block_m, M)
    bf = pick_block(block_f, F)
    in_specs, *_, h_spec = _specs(E, F, bm, bf)
    inputs = (x2, wg, bg2, wx, bx2)
    return pl.pallas_call(
        _ffn_fwd_kernel,
        grid=(F // bf, M // bm),
        in_specs=in_specs,
        out_shape=jax.ShapeDtypeStruct((M, F), x2.dtype),
        out_specs=h_spec,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*inputs)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _ffn_bwd_kernel(*refs):
    """Recompute g/t for one tile pair, emit the pre-activation
    cotangents (dg, dt — consumed by the XLA ``@ W^T`` contractions for
    dx), and accumulate fp32 dWg/dbg/dWx/dbx across the row grid while
    the weight column blocks are resident."""
    (x_ref, wg_ref, bg_ref, wx_ref, bx_ref, gh_ref,
     dg_ref, dt_ref, dwg_ref, dbg_ref, dwx_ref, dbx_ref) = refs
    xn = x_ref[...]
    i = pl.program_id(1)
    g, t = _pre_acts(xn, wg_ref, bg_ref, wx_ref, bx_ref)
    sg = jax.nn.sigmoid(g)
    silu = g * sg
    gh = gh_ref[...].astype(jnp.float32)
    dg = gh * t * (sg * (1.0 + g * (1.0 - sg)))  # d silu(g) = sg(1+g(1-sg))
    dt = gh * silu
    dg_lp = dg.astype(dg_ref.dtype)  # low-precision twin: what XLA's
    dt_lp = dt.astype(dt_ref.dtype)  # un-fused backward would carry
    dg_ref[...] = dg_lp
    dt_ref[...] = dt_lp
    pwg = jax.lax.dot_general(
        xn, dg_lp, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (E, bf) fp32
    pwx = jax.lax.dot_general(
        xn, dt_lp, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    pbg = jnp.sum(dg, axis=0, keepdims=True)
    pbx = jnp.sum(dt, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _init():
        dwg_ref[...] = pwg
        dbg_ref[...] = pbg
        dwx_ref[...] = pwx
        dbx_ref[...] = pbx

    @pl.when(i > 0)
    def _acc():
        dwg_ref[...] += pwg
        dbg_ref[...] += pbg
        dwx_ref[...] += pwx
        dbx_ref[...] += pbx


def _bwd_call(x2, wg, bg2, wx, bx2, gh, *, block_m, block_f, interpret):
    M, E = x2.shape
    F = wg.shape[1]
    bm = pick_block(block_m, M)
    bf = pick_block(block_f, F)
    in_specs, x_spec, w_spec, b_spec, h_spec = _specs(E, F, bm, bf)
    in_specs = in_specs + [h_spec]
    inputs = (x2, wg, bg2, wx, bx2, gh)
    dwb_spec = pl.BlockSpec((1, bf), lambda j, i: (0, j), memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _ffn_bwd_kernel,
        grid=(F // bf, M // bm),
        in_specs=in_specs,
        out_shape=[
            jax.ShapeDtypeStruct((M, F), x2.dtype),       # dg
            jax.ShapeDtypeStruct((M, F), x2.dtype),       # dt
            jax.ShapeDtypeStruct((E, F), jnp.float32),    # dWg
            jax.ShapeDtypeStruct((1, F), jnp.float32),    # dbg
            jax.ShapeDtypeStruct((E, F), jnp.float32),    # dWx
            jax.ShapeDtypeStruct((1, F), jnp.float32),    # dbx
        ],
        out_specs=[h_spec, h_spec, w_spec, dwb_spec, w_spec, dwb_spec],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(*inputs)


def _dxn(dg, dt, wg, wx):
    """dg @ Wg^T + dt @ Wx^T in the stored dtype (what the un-fused XLA
    backward carries), fp32 MXU accumulation."""
    out = jax.lax.dot_general(
        dg, wg, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + jax.lax.dot_general(
        dt, wx, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.astype(dg.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wrappers (2D) — the public API reshapes
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _swiglu2(x2, wg, bg2, wx, bx2, block_m, block_f, interpret):
    return _fwd_call(
        x2, wg, bg2, wx, bx2,
        block_m=block_m, block_f=block_f, interpret=interpret,
    )


def _swiglu2_fwd(x2, wg, bg2, wx, bx2, block_m, block_f, interpret):
    h = _swiglu2(x2, wg, bg2, wx, bx2, block_m, block_f, interpret)
    return h, (x2, wg, bg2, wx, bx2)


def _swiglu2_bwd(block_m, block_f, interpret, res, gh):
    x2, wg, bg2, wx, bx2 = res
    dg, dt, dwg, dbg, dwx, dbx = _bwd_call(
        x2, wg, bg2, wx, bx2, gh,
        block_m=block_m, block_f=block_f, interpret=interpret,
    )
    dx = _dxn(dg, dt, wg, wx)
    return (dx, dwg.astype(wg.dtype), dbg.astype(bg2.dtype),
            dwx.astype(wx.dtype), dbx.astype(bx2.dtype))


_swiglu2.defvjp(_swiglu2_fwd, _swiglu2_bwd)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def fused_swiglu(
    x: jnp.ndarray,
    w_gate: jnp.ndarray,
    b_gate: jnp.ndarray,
    w_xform: jnp.ndarray,
    b_xform: jnp.ndarray,
    *,
    block_m: int = _DEFAULT_BLOCK_M,
    block_f: int = _DEFAULT_BLOCK_F,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Fused ``silu(x @ Wg + bg) * (x @ Wx + bx)`` (ops/swiglu.py's math,
    one HBM pass over the activation per hidden tile). ``x``: (..., E);
    weights (E, F) — cast to ``x.dtype`` here exactly like
    ``models/common.apply_ffn`` does before the reference op."""
    if interpret is None:
        interpret = auto_interpret()
    E = x.shape[-1]
    x2 = x.reshape(-1, E)
    h = _swiglu2(
        x2,
        w_gate.astype(x.dtype), b_gate.astype(x.dtype).reshape(1, -1),
        w_xform.astype(x.dtype), b_xform.astype(x.dtype).reshape(1, -1),
        block_m, block_f, interpret,
    )
    return h.reshape(x.shape[:-1] + (w_gate.shape[1],))
