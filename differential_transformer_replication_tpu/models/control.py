"""StandardTransformer: the vanilla-attention control model.

Functional JAX re-design of control.py:113-171 — decoder-only LM with
RoPE as the only position encoding (no position table, control.py:118-119,
143-144), pre-LN residual blocks, SwiGLU FFN, untied lm_head.

All heads are computed in one merged einsum instead of the reference's
per-head Python loop (control.py:76).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import common
from differential_transformer_replication_tpu.ops import (
    apply_rope,
    causal_mask,
    rope_cos_sin,
    vanilla_attention,
)
from differential_transformer_replication_tpu.ops.streams import vanilla_coeffs


# RoPE is this family's position encoding (control.py:47-48); consumers
# that precompute the tables (parallel/pipeline.py) key on this flag.
USES_ROPE = True


def init(key: jax.Array, cfg: ModelConfig) -> dict:
    H, d, E = cfg.n_head, cfg.head_size, cfg.n_embd
    keys = jax.random.split(key, cfg.n_layer + 3)
    blocks = []
    for li in range(cfg.n_layer):
        kq, kk, kv, ko, kf = jax.random.split(keys[li], 5)
        blocks.append(
            {
                "ln1": common.layer_norm_params(E),
                "attn": {
                    # merged per-head K/Q/V projections, no bias
                    # (control.py:28-30)
                    "wq": common.normal_init(kq, (E, H, d)),
                    "wk": common.normal_init(kk, (E, H, d)),
                    "wv": common.normal_init(kv, (E, H, d)),
                    # out-proj Linear(head_size*num_heads, n_embd) with bias
                    # (control.py:72)
                    "out": common.linear_params(ko, H * d, E),
                },
                "ln2": common.layer_norm_params(E),
                "ffn": common.ffn_params(kf, E),
            }
        )
    return {
        "tok_emb": common.normal_init(keys[-3], (cfg.vocab_size, E)),
        "blocks": blocks,
        "ln_f": common.layer_norm_params(E),
        "lm_head": common.linear_params(keys[-1], E, cfg.vocab_size),
    }


def _attn(
    x: jnp.ndarray,
    p: dict,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: jnp.ndarray,
    dropout_rate: float,
    rng: Optional[jax.Array],
    impl: str = "xla",
    mesh=None,
    seq_impl: str = "ring",
) -> jnp.ndarray:
    B, T, E = x.shape
    r_att, r_out = common.split_rng(rng, 2)
    q = jnp.einsum("bte,ehd->bthd", x, p["wq"].astype(x.dtype))
    k = jnp.einsum("bte,ehd->bthd", x, p["wk"].astype(x.dtype))
    v = jnp.einsum("bte,ehd->bthd", x, p["wv"].astype(x.dtype))
    q = apply_rope(q, cos, sin)  # control.py:47-48
    k = apply_rope(k, cos, sin)
    coeffs = vanilla_coeffs(q.shape[2])
    out = common.dispatch_attention(
        q[None], k[None], v, coeffs,
        # the dense XLA reference op (control.py:52-62)
        lambda: vanilla_attention(
            q, k, v, mask=mask, dropout_rate=dropout_rate, rng=r_att
        ),
        impl=impl, mesh=mesh, dropout_rate=dropout_rate, rng=r_att,
        seq_impl=seq_impl,
        # kernel-native-layout fast path (RoPE applied in the bh layout)
        flash_fn=common.flash_bh_fn(
            x, p["wq"][None], p["wk"][None], p["wv"], coeffs,
            dropout_rate=dropout_rate, rng=r_att, cos=cos, sin=sin,
        ),
    )
    out = out.reshape(B, T, -1)  # concat heads (control.py:76)
    out = common.linear(out, p["out"])
    return common.dropout(out, dropout_rate, r_out)  # control.py:77


def embed(params: dict, idx: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Token embedding only — RoPE is the position encoding
    (control.py:144, no position table)."""
    return params["tok_emb"][idx].astype(jnp.dtype(cfg.compute_dtype))


def block_forward(
    x: jnp.ndarray,
    blk: dict,
    layer_idx,
    cfg: ModelConfig,
    cos: Optional[jnp.ndarray],
    sin: Optional[jnp.ndarray],
    mask: jnp.ndarray,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> jnp.ndarray:
    """One pre-LN residual block (control.py:92-111). ``layer_idx`` is part
    of the uniform per-family signature (models/registry.py); the control
    model has no per-layer schedule, so it is unused here."""
    del layer_idx
    r_attn, r_ffn = common.split_rng(rng, 2)
    a = _attn(
        common.apply_pre_norm(x, blk["ln1"], cfg, mesh), blk["attn"],
        cos, sin, mask, cfg.dropout, r_attn, cfg.attention_impl, mesh,
        cfg.sequence_impl,
    )
    # residual add + ln2 + SwiGLU + down-proj + residual, ffn_impl-
    # dispatched (fused kernels when "pallas"; models/common.py)
    return common.apply_block_ffn(x, a, blk, cfg, r_ffn, mesh)


def forward(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    targets: Optional[jnp.ndarray] = None,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """(B, T) int tokens -> (logits (B, T, V), loss or None)."""
    B, T = idx.shape
    x = embed(params, idx, cfg)
    cos, sin = rope_cos_sin(cfg.head_size, T)
    mask = causal_mask(T)
    rngs = common.split_rng(rng, cfg.n_layer)
    for li, (blk, r) in enumerate(zip(params["blocks"], rngs), 1):
        fn = block_forward
        if cfg.remat:  # recompute this block's activations in the backward
            fn = common.remat_block(fn, cfg)  # cfg.remat_policy-aware
        x = fn(x, blk, li, cfg, cos, sin, mask, r, mesh)
    return common.tail_and_loss(x, params, cfg, targets, mesh)
