"""AlternatingDiffTransformer: the N-term differential generalization.

Functional JAX re-design of Ndiff_transformer.py:181-265. Distinctive
reference behaviors preserved:
  - RoPE position encoding, no position table (Ndiff_transformer.py:188,
    104-110),
  - n_terms Q/K projection pairs with a single doubled value
    (Ndiff_transformer.py:49-59), here stacked on a leading term axis and
    computed in ONE batched attention call instead of the per-term loop,
  - the lambda chain where term i subtracts term i-1's exponential
    (Ndiff_transformer.py:85-93),
  - the combination scales the FIRST map by lambda_0 (not 1), with
    alternating signs after (Ndiff_transformer.py:119-123) — so n_terms=2
    is intentionally NOT numerically identical to the 2-term diff model,
  - full-width GroupLayerNorm + constant 0.2 output scale
    (Ndiff_transformer.py:143-144).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import common
from differential_transformer_replication_tpu.ops import (
    apply_rope,
    causal_mask,
    lambda_init_schedule,
    ndiff_attention,
    ndiff_lambdas,
    ndiff_signs,
    rope_cos_sin,
)
from differential_transformer_replication_tpu.ops.lambdas import OUTPUT_SCALE
from differential_transformer_replication_tpu.ops.streams import ndiff_coeffs


# RoPE positions (Ndiff_transformer.py:104-110); consumers that precompute
# the tables (parallel/pipeline.py) key on this flag.
USES_ROPE = True


def init(key: jax.Array, cfg: ModelConfig) -> dict:
    H, d, E, n = cfg.n_head, cfg.head_size, cfg.n_embd, cfg.n_terms
    keys = jax.random.split(key, cfg.n_layer + 3)
    blocks = []
    for li in range(cfg.n_layer):
        kq, kk, kv, ko, kf = jax.random.split(keys[li], 5)
        blocks.append(
            {
                "ln1": common.layer_norm_params(E),
                "attn": {
                    # n_terms Q/K projections (Ndiff_transformer.py:49-56)
                    "wq": common.normal_init(kq, (n, E, H, d)),
                    "wk": common.normal_init(kk, (n, E, H, d)),
                    "wv": common.normal_init(kv, (E, H, 2 * d)),
                    # per-term lambda vectors (Ndiff_transformer.py:64-71)
                    "lambda_q": jnp.zeros((n, H, d), jnp.float32),
                    "lambda_k": jnp.zeros((n, H, d), jnp.float32),
                    "gn": common.layer_norm_params(H * 2 * d),
                    "out": common.linear_params(ko, H * 2 * d, E),
                },
                "ln2": common.layer_norm_params(E),
                "ffn": common.ffn_params(kf, E),
            }
        )
    return {
        "tok_emb": common.normal_init(keys[-3], (cfg.vocab_size, E)),
        "blocks": blocks,
        "ln_f": common.layer_norm_params(E),
        "lm_head": common.linear_params(keys[-1], E, cfg.vocab_size),
    }


def _attn(
    x: jnp.ndarray,
    p: dict,
    layer_idx: int,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    mask: jnp.ndarray,
    dropout_rate: float,
    rng: Optional[jax.Array],
    impl: str = "xla",
    mesh=None,
    seq_impl: str = "ring",
    cfg=None,
) -> jnp.ndarray:
    B, T, E = x.shape
    n = p["wq"].shape[0]
    r_att, r_out = common.split_rng(rng, 2)
    qs = jnp.einsum("bte,nehd->nbthd", x, p["wq"].astype(x.dtype))
    ks = jnp.einsum("bte,nehd->nbthd", x, p["wk"].astype(x.dtype))
    v = jnp.einsum("bte,ehd->bthd", x, p["wv"].astype(x.dtype))
    # RoPE per term/head (Ndiff_transformer.py:108-110); tables broadcast
    # over the leading term axis.
    qs = apply_rope(qs, cos, sin)
    ks = apply_rope(ks, cos, sin)
    lams = ndiff_lambdas(p["lambda_q"], p["lambda_k"], lambda_init_schedule(layer_idx))
    coeffs = ndiff_coeffs(lams, ndiff_signs(n))
    out = common.dispatch_attention(
        qs, ks, v, coeffs,
        # the dense XLA reference op (Ndiff_transformer.py:95-126)
        lambda: ndiff_attention(
            qs, ks, v, lams, ndiff_signs(n),
            mask=mask, dropout_rate=dropout_rate, rng=r_att,
        ),
        impl=impl, mesh=mesh, dropout_rate=dropout_rate, rng=r_att,
        seq_impl=seq_impl,
        # kernel-native-layout fast path (RoPE applied in the bh layout)
        flash_fn=common.flash_bh_fn(
            x, p["wq"], p["wk"], p["wv"], coeffs,
            dropout_rate=dropout_rate, rng=r_att, cos=cos, sin=sin,
        ),
    )
    out = out.reshape(B, T, -1)  # concat heads (Ndiff_transformer.py:142)
    out = common.apply_group_norm(out, p["gn"], cfg, mesh)  # :143
    out = out * OUTPUT_SCALE  # constant 0.2, :144
    out = common.linear(out, p["out"])
    return common.dropout(out, dropout_rate, r_out)


def embed(params: dict, idx: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Token embedding only — RoPE positions (Ndiff_transformer.py:188, 213)."""
    return params["tok_emb"][idx].astype(jnp.dtype(cfg.compute_dtype))


def block_forward(
    x: jnp.ndarray,
    blk: dict,
    layer_idx,
    cfg: ModelConfig,
    cos: Optional[jnp.ndarray],
    sin: Optional[jnp.ndarray],
    mask: jnp.ndarray,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> jnp.ndarray:
    """One pre-LN residual block (Ndiff_transformer.py:160-179).
    ``layer_idx`` is 1-based (Ndiff_transformer.py:216) and may be static
    or traced (the pipeline-parallel layer scan)."""
    r_attn, r_ffn = common.split_rng(rng, 2)
    a = _attn(
        common.apply_pre_norm(x, blk["ln1"], cfg, mesh), blk["attn"],
        layer_idx, cos, sin, mask, cfg.dropout, r_attn, cfg.attention_impl,
        mesh, cfg.sequence_impl, cfg,
    )
    # residual add + ln2 + SwiGLU + down-proj + residual, ffn_impl-
    # dispatched (fused kernels when "pallas"; models/common.py)
    return common.apply_block_ffn(x, a, blk, cfg, r_ffn, mesh)


def forward(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    targets: Optional[jnp.ndarray] = None,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """(B, T) int tokens -> (logits (B, T, V), loss or None)."""
    B, T = idx.shape
    x = embed(params, idx, cfg)
    cos, sin = rope_cos_sin(cfg.head_size, T)
    mask = causal_mask(T)
    rngs = common.split_rng(rng, cfg.n_layer)
    for li, (blk, r) in enumerate(zip(params["blocks"], rngs), 1):  # 1-based, :216
        fn = block_forward
        if cfg.remat:  # recompute this block's activations in the backward
            fn = common.remat_block(fn, cfg)  # cfg.remat_policy-aware
        x = fn(x, blk, li, cfg, cos, sin, mask, r, mesh)
    return common.tail_and_loss(x, params, cfg, targets, mesh)
