"""Model-select switch.

The reference selects models by commenting code blocks in and out
(train.py:205-230); here it is a first-class dispatch on
``ModelConfig.model`` covering the same three families.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import control, diff, ndiff

_MODULES = {"control": control, "diff": diff, "ndiff": ndiff}


def init_model(key: jax.Array, cfg: ModelConfig) -> dict:
    return _MODULES[cfg.model].init(key, cfg)


def model_forward(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    targets: Optional[jnp.ndarray] = None,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """``mesh`` (jax.sharding.Mesh, optional): when it carries a >1
    ``sequence`` axis, attention runs ring-sharded over it
    (parallel/ring.py); otherwise it is ignored."""
    return _MODULES[cfg.model].forward(
        params, idx, cfg, targets=targets, rng=rng, mesh=mesh
    )


def param_count(params: dict) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def model_module(cfg: ModelConfig):
    """The family's module, exposing the split forward pieces each family
    defines with a uniform signature — ``embed(params, idx, cfg)`` and
    ``block_forward(x, blk, layer_idx, cfg, cos, sin, mask, rng, mesh)`` —
    used by the pipeline-parallel schedule (parallel/pipeline.py), which
    must place embed / blocks / lm-head on different stages."""
    return _MODULES[cfg.model]
