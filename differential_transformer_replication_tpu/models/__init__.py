from differential_transformer_replication_tpu.models.registry import (
    init_model,
    model_forward,
    model_module,
    param_count,
)
from differential_transformer_replication_tpu.models.generate import generate
from differential_transformer_replication_tpu.models.decode import (
    forward_chunk,
    generate_cached,
    init_cache,
)

__all__ = [
    "init_model",
    "model_forward",
    "model_module",
    "param_count",
    "generate",
    "generate_cached",
    "forward_chunk",
    "init_cache",
]
