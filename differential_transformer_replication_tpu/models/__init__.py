from differential_transformer_replication_tpu.models.registry import (
    init_model,
    model_forward,
    param_count,
)
from differential_transformer_replication_tpu.models.generate import generate

__all__ = ["init_model", "model_forward", "param_count", "generate"]
