"""Shared model plumbing: parameter init, linear/FFN application, loss.

Init parity with the reference's ``_init_weights`` (control.py:132-138,
identical in the other two files): every Linear weight ~ N(0, 0.02), every
Linear bias zero, embeddings ~ N(0, 0.02). LayerNorm weights/biases start
at ones/zeros, and the lambda vectors start at zero (diff_transformer.py:
35-38) — ``_init_weights`` only touches Linear/Embedding modules, so those
defaults survive in the reference too.

Weights are stored ``(in, out)`` so application is ``x @ W + b`` (the
transpose of torch's ``(out, in)`` storage; same distribution at init
since entries are iid).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.ops import (
    fused_group_norm,
    group_layer_norm,
    layer_norm,
    swiglu,
)
from differential_transformer_replication_tpu.ops.dropout import dropout
from differential_transformer_replication_tpu.ops.fused_ffn import fused_swiglu
from differential_transformer_replication_tpu.ops.fused_norm_residual import (
    fused_add_norm,
    fused_norm,
)
from differential_transformer_replication_tpu.ops.losses import (
    fused_linear_cross_entropy,
)

INIT_STD = 0.02  # control.py:134


def normal_init(key: jax.Array, shape, std: float = INIT_STD) -> jnp.ndarray:
    return jax.random.normal(key, shape, dtype=jnp.float32) * std


def linear_params(key: jax.Array, in_dim: int, out_dim: int, bias: bool = True) -> dict:
    p = {"w": normal_init(key, (in_dim, out_dim))}
    if bias:
        p["b"] = jnp.zeros((out_dim,), jnp.float32)
    return p


def linear(x: jnp.ndarray, p: dict) -> jnp.ndarray:
    y = x @ p["w"].astype(x.dtype)
    if "b" in p:
        y = y + p["b"].astype(x.dtype)
    return y


def layer_norm_params(dim: int) -> dict:
    return {"w": jnp.ones((dim,), jnp.float32), "b": jnp.zeros((dim,), jnp.float32)}


def apply_layer_norm(x: jnp.ndarray, p: dict) -> jnp.ndarray:
    return layer_norm(x, p["w"], p["b"])


def ffn_params(key: jax.Array, n_embd: int) -> dict:
    """The reference FFN: SwiGLU(n_embd -> 4*n_embd) then Linear(4*n_embd ->
    n_embd) then Dropout (control.py:100-104). All three linears carry
    biases (nn.Linear defaults)."""
    kg, kx, ko = jax.random.split(key, 3)
    return {
        "gate": linear_params(kg, n_embd, 4 * n_embd),
        "xform": linear_params(kx, n_embd, 4 * n_embd),
        "out": linear_params(ko, 4 * n_embd, n_embd),
    }


def apply_ffn(
    x: jnp.ndarray,
    p: dict,
    dropout_rate: float = 0.0,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    h = swiglu(
        x,
        p["gate"]["w"].astype(x.dtype), p["gate"]["b"].astype(x.dtype),
        p["xform"]["w"].astype(x.dtype), p["xform"]["b"].astype(x.dtype),
    )
    out = linear(h, p["out"])
    return dropout(out, dropout_rate, rng)


# ---------------------------------------------------------------------------
# ffn_impl dispatch — the fused non-attention hot path (ISSUE 9 / ROADMAP
# item 5). "xla" is the reference composition above; "pallas" routes the
# block-boundary residual-add + LayerNorm through the single-pass kernel
# (ops/fused_norm_residual.py) and the SwiGLU chain through the fused
# MXU kernel (ops/fused_ffn.py). Selection mirrors attention_impl: one
# ModelConfig switch, all three families + decode.


def use_fused_ffn(cfg, mesh=None) -> bool:
    """Whether the fused Pallas FFN/norm kernels may be dispatched here.

    GSPMD cannot partition a bare ``pallas_call`` — the reason
    ``attention_impl='pallas'`` routes through the shard_map wrapper
    (parallel/shard_flash.py) on >1-device meshes. The fused FFN/norm
    kernels have no such wrapper, so any multi-device GSPMD placement
    (fsdp/tensor/sequence/pipeline, multi-process DP, or pure DP with
    ``dp_overlap`` off) falls back to the XLA composition — numerically
    identical, just un-fused. The overlap-DP hot path is unaffected:
    its shard_map body runs with ``mesh=None`` (every shard is a
    single-device program), so the fused kernels stay on there.
    """
    if cfg is None or cfg.ffn_impl != "pallas":
        return False
    return mesh is None or mesh.devices.size == 1


def apply_pre_norm(x: jnp.ndarray, p: dict, cfg, mesh=None) -> jnp.ndarray:
    """A standalone LayerNorm with no residual input — the block's first
    pre-LN and decode's ln_f — dispatched on ``cfg.ffn_impl``."""
    if use_fused_ffn(cfg, mesh):
        return fused_norm(x, p["w"], p["b"])
    return layer_norm(x, p["w"], p["b"])


def apply_group_norm(x: jnp.ndarray, p: dict, cfg, mesh=None) -> jnp.ndarray:
    """The full-width GroupLayerNorm over the head concat (diff/ndiff
    attention + decode), dispatched like :func:`apply_pre_norm` — the
    Pallas GLN is the fused_norm alias (ops/fused_norm_residual.py)."""
    if use_fused_ffn(cfg, mesh):
        return fused_group_norm(x, p["w"], p["b"])
    return group_layer_norm(x, p["w"], p["b"])


def apply_block_ffn(
    x: jnp.ndarray,
    attn_out: jnp.ndarray,
    blk: dict,
    cfg,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> jnp.ndarray:
    """The block's FFN half: attention residual add + pre-LN + SwiGLU +
    down-proj + dropout + FFN residual add (control.py:92-111's second
    half, identical across families).

    On the fused path the first three HBM round-trips collapse into two
    kernels: ``fused_add_norm`` produces the carried residual AND the
    normalized FFN input in one pass over the tile, and ``fused_swiglu``
    runs the gate/xform/SiLU/product chain without materializing the
    (M, 4E) pre-activations. The down-proj + residual stay XLA: the
    row-parallel matmul is MXU-bound and XLA fuses the add into its
    epilogue.
    """
    rate = cfg.dropout
    if use_fused_ffn(cfg, mesh):
        p = blk["ffn"]
        x, normed = fused_add_norm(
            x, attn_out, blk["ln2"]["w"], blk["ln2"]["b"]
        )
        h = fused_swiglu(
            normed,
            p["gate"]["w"], p["gate"]["b"],
            p["xform"]["w"], p["xform"]["b"],
        )
        return x + dropout(linear(h, p["out"]), rate, rng)
    x = x + attn_out
    return x + apply_ffn(
        apply_layer_norm(x, blk["ln2"]), blk["ffn"], rate, rng
    )


# jax.checkpoint policies selectable per run (ModelConfig.remat_policy):
# what the block remat may SAVE instead of recomputing. Resolved lazily —
# jax.checkpoint_policies is stable across the pinned versions.
REMAT_POLICIES = ("none", "dots", "dots_no_batch", "nothing", "everything")


def resolve_remat_policy(name: str):
    cp = jax.checkpoint_policies
    return {
        "none": None,  # jax.checkpoint default: save block inputs only
        "dots": cp.dots_saveable,
        "dots_no_batch": cp.dots_with_no_batch_dims_saveable,
        "nothing": cp.nothing_saveable,
        "everything": cp.everything_saveable,
    }[name]


def remat_block(block_fn, cfg):
    """Wrap a family's ``block_forward`` in jax.checkpoint under the
    configured save policy. static_argnums pins (layer_idx, cfg, mesh) —
    the uniform per-family block signature (models/registry.py)."""
    policy = resolve_remat_policy(cfg.remat_policy)
    kw = {} if policy is None else {"policy": policy}
    return jax.checkpoint(block_fn, static_argnums=(2, 3, 8), **kw)




def apply_tail(x: jnp.ndarray, params: dict, cfg=None, mesh=None) -> jnp.ndarray:
    """Final LayerNorm + untied lm head — identical across the three
    families (control.py:126-127, diff_transformer.py:164-165,
    Ndiff_transformer.py:220-221). ``params`` is the model params dict
    (or any dict carrying ``ln_f``/``lm_head``). The ln_f dispatches on
    ``cfg.ffn_impl`` like every block-boundary norm (``cfg=None`` =
    reference path)."""
    x = apply_pre_norm(x, params["ln_f"], cfg, mesh)
    return linear(x, params["lm_head"])


def fused_tail_loss(
    x: jnp.ndarray, params: dict, targets: jnp.ndarray, chunk: int,
    cfg=None, mesh=None,
) -> jnp.ndarray:
    """Final LayerNorm + chunked fused lm-head/cross-entropy
    (ops/losses.py) — the loss of :func:`apply_tail` +
    :func:`cross_entropy_loss` without ever materializing (B, T, V)
    logits."""
    x = apply_pre_norm(x, params["ln_f"], cfg, mesh)
    p = params["lm_head"]
    return fused_linear_cross_entropy(x, p["w"], p.get("b"), targets, chunk)


def _ce_primal(logits: jnp.ndarray, targets: jnp.ndarray):
    logits32 = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits32, axis=-1)  # (B, T)
    tgt = jnp.take_along_axis(logits32, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt), lse


@jax.custom_vjp
def cross_entropy_loss(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Mean cross-entropy over all (B*T) positions, matching the flattened
    ``F.cross_entropy`` call (control.py:153-159). Computed in float32 as
    ``mean(logsumexp - target_logit)``.

    Custom VJP: autodiff of the logsumexp materializes the softmax as a
    full (B, T, V) float32 tensor before the cast to the logits dtype —
    at the recipe scale that is a 786 MB HBM round-trip worth ~2% of the
    train step (profiled). The hand-written backward emits
    ``(softmax - onehot) * g / N`` directly in the logits dtype, which
    XLA fuses into a single elementwise pass over the logits."""
    loss, _ = _ce_primal(logits, targets)
    return loss


def _ce_fwd(logits, targets):
    loss, lse = _ce_primal(logits, targets)
    return loss, (logits, lse, targets)


def _ce_bwd(res, g):
    logits, lse, targets = res
    n = logits.size // logits.shape[-1]
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    d = (p - (iota == targets[..., None]).astype(jnp.float32)) * (g / n)
    return d.astype(logits.dtype), None


cross_entropy_loss.defvjp(_ce_fwd, _ce_bwd)


def tail_and_loss(x, params: dict, cfg, targets, mesh=None):
    """The shared end-of-forward dispatch for all three families: final
    LayerNorm + lm head + (optional) loss. With ``cfg.loss_chunk`` set and
    targets given, routes through the fused chunked loss (ops/losses.py)
    and returns ``(None, loss)`` — logits are never materialized by
    design. Otherwise the reference's dense shape: ``(logits, loss|None)``
    (control.py:147-159); the dense loss runs through
    ``dense_linear_cross_entropy`` (ops/losses.py), whose hand-written
    head backward skips XLA's fp32 transposed grad materialization, and
    the returned logits are an independent dense head application that
    training steps drop (DCE removes it when only the loss is consumed)."""
    if targets is not None and cfg.loss_chunk:
        return None, fused_tail_loss(
            x, params, targets, cfg.loss_chunk, cfg, mesh
        )
    if targets is not None:
        from differential_transformer_replication_tpu.ops.losses import (
            dense_linear_cross_entropy,
        )

        x_ln = apply_pre_norm(x, params["ln_f"], cfg, mesh)
        p = params["lm_head"]
        loss = dense_linear_cross_entropy(x_ln, p["w"], p.get("b"), targets)
        return linear(x_ln, p), loss
    return apply_tail(x, params, cfg, mesh), None


def split_rng(rng: Optional[jax.Array], n: int):
    """Split an optional dropout rng into n optional keys."""
    if rng is None:
        return (None,) * n
    return tuple(jax.random.split(rng, n))


def flash_bh_fn(
    x: jnp.ndarray,  # (B, T, E) normed block input
    wq: jnp.ndarray,  # (S, E, H, d) stacked query projections
    wk: jnp.ndarray,  # (S, E, H, d)
    wv: jnp.ndarray,  # (E, H, dv)
    coeffs: jnp.ndarray,  # (S, H) float32
    *,
    dropout_rate: float,
    rng,
    cos=None,  # RoPE tables (families without RoPE pass None)
    sin=None,
):
    """Build the ``flash_fn`` closure for :func:`dispatch_attention`: the
    kernel-native-layout fast path, shared by ALL THREE families
    (VERDICT r2 item 5 — it was diff-only, leaving the control half of
    every PPL-gap experiment slower by construction).

    Projects straight into the kernel's (B*H, S, T, d) layout — einsum
    ``"bte,sehd->bhstd"`` + free reshape — instead of transposing the
    stacked (S, B, T, H, d) arrays the dense path builds (XLA does not
    eliminate those copies; profiled ~0.5-1 ms at recipe scale). RoPE
    families rotate in the bh layout itself (``headed=False``: tables
    broadcast over the fused batch*head axis), so no layout round-trip
    sneaks back in."""

    def _fn():
        from differential_transformer_replication_tpu.ops.flash import (
            multi_stream_flash_attention_bh,
            multi_stream_flash_attention_tm,
            tm_packed_ok,
            use_tm,
        )
        from differential_transformer_replication_tpu.ops.rope import apply_rope

        B, T, E = x.shape
        S, _, H, d = wq.shape
        dv = wv.shape[-1]
        rate_live = dropout_rate if rng is not None else 0.0
        # Ineligible shapes (exotic dv/d offset ratios, narrow lane
        # widths — see tm_packed_ok) fall through to the per-array tm
        # path instead of tripping the kernel's spec assert at trace time.
        if use_tm(S, T, rate_live) and cos is None and tm_packed_ok(S, H, d, dv):
            # PACKED token-major fast path (no-RoPE families): ONE fused
            # projection matmul x @ [Wq..|Wk..|Wv]; the kernel reads
            # column windows of its output and the backward emits one
            # packed dproj — zero copies on either side
            from differential_transformer_replication_tpu.ops.flash import (
                multi_stream_flash_attention_tm_packed,
            )

            wcat = jnp.concatenate(
                [wq[s].reshape(E, H * d) for s in range(S)]
                + [wk[s].reshape(E, H * d) for s in range(S)]
                + [wv.reshape(E, H * dv)],
                axis=1,
            ).astype(x.dtype)
            proj = x @ wcat  # (B, T, 2*S*H*d + H*dv)
            return multi_stream_flash_attention_tm_packed(
                proj, coeffs, B, H, S, d, dv
            )
        if use_tm(S, T, rate_live):
            # TOKEN-MAJOR fast path (ops/flash.py tm kernels): each
            # projection's matmul output feeds the kernel after a pure
            # reshape — no (B,T,H,d)->(B,H,T,d) transposes fwd or bwd, and
            # the (B,T,H,dv) output keeps the GroupLayerNorm reduce and
            # the out-projection contiguous (round-4 profile: ~660 MB/step
            # of HBM transpose copies + a 4.5 ms strided stat reduce on
            # the head-major path at recipe scale)
            wq_c = wq.astype(x.dtype)
            wk_c = wk.astype(x.dtype)
            qs = tuple(
                (x @ wq_c[s].reshape(E, H * d)).reshape(B, T, H, d)
                for s in range(S)
            )
            ks = tuple(
                (x @ wk_c[s].reshape(E, H * d)).reshape(B, T, H, d)
                for s in range(S)
            )
            v_tm = (x @ wv.astype(x.dtype).reshape(E, H * dv)).reshape(
                B, T, H, dv
            )
            if cos is not None:
                qs = tuple(apply_rope(q, cos, sin, headed=True) for q in qs)
                ks = tuple(apply_rope(k, cos, sin, headed=True) for k in ks)
            return multi_stream_flash_attention_tm(qs, ks, v_tm, coeffs, B, H)
        q_r = jnp.einsum("bte,sehd->bhstd", x, wq.astype(x.dtype)).reshape(
            B * H, S, T, d
        )
        k_r = jnp.einsum("bte,sehd->bhstd", x, wk.astype(x.dtype)).reshape(
            B * H, S, T, d
        )
        v_r = jnp.einsum("bte,ehd->bhtd", x, wv.astype(x.dtype)).reshape(
            B * H, T, dv
        )
        if cos is not None:
            q_r = apply_rope(q_r, cos, sin, headed=False)
            k_r = apply_rope(k_r, cos, sin, headed=False)
        out = multi_stream_flash_attention_bh(
            q_r, k_r, v_r, coeffs, B, H,
            dropout_rate=dropout_rate, dropout_rng=rng,
        )
        return out.reshape(B, H, T, dv).transpose(0, 2, 1, 3)

    return _fn


def dispatch_attention(
    qs: jnp.ndarray,  # (S, B, T, H, d) stacked streams
    ks: jnp.ndarray,  # (S, B, T, H, d)
    v: jnp.ndarray,  # (B, T, H, dv)
    coeffs: jnp.ndarray,  # (S, H) float32 combine coefficients
    dense_fn,
    *,
    impl: str,
    mesh,
    dropout_rate: float,
    rng: Optional[jax.Array],
    flash_fn=None,
    seq_impl: str = "ring",
) -> jnp.ndarray:
    """The attention-backend dispatch shared by all three families.

    Every family's attention is the same multi-stream form
    (ops/streams.py), so backend selection is family-independent:
      1. >1 ``sequence`` mesh axis  -> sequence parallelism: ring
         attention (parallel/ring.py) or, with seq_impl == "ulysses",
         all-to-all re-sharding (parallel/ulysses.py),
      2. impl == "pallas", >1-device mesh -> shard_map'd flash
         (parallel/shard_flash.py),
      3. impl == "pallas"           -> fused flash kernel (ops/flash.py),
      4. otherwise                  -> ``dense_fn()``, the family's XLA
         reference op (ops/attention.py) closed over its own arguments.
    All parallel backends take the dropout (rate, rng) pair; dense_fn
    applies its own dropout internally.

    ``flash_fn`` (optional, () -> (B, T, H, dv)) overrides branch 3: a
    family that can project straight into the kernel's (B*H, S, T, d)
    layout supplies a closure calling multi_stream_flash_attention_bh,
    skipping the stacked-layout transposes on the hot single-device path
    (XLA does not eliminate them otherwise; see models/diff.py).
    """
    # lazy import: parallel/__init__ pulls in the training stack, which
    # imports models — importing at call (trace) time breaks the cycle
    from differential_transformer_replication_tpu.ops.flash import (
        multi_stream_flash_attention,
        use_flash,
    )
    from differential_transformer_replication_tpu.parallel.ring import (
        ring_multi_stream_attention,
        use_ring,
    )
    from differential_transformer_replication_tpu.parallel.shard_flash import (
        shard_flash_multi_stream_attention,
        use_shard_flash,
    )

    if use_ring(mesh):
        if seq_impl == "ulysses":
            from differential_transformer_replication_tpu.parallel.ulysses import (
                ulysses_multi_stream_attention,
            )

            return ulysses_multi_stream_attention(
                qs, ks, v, coeffs, mesh, impl,
                dropout_rate=dropout_rate, dropout_rng=rng,
            )
        return ring_multi_stream_attention(
            qs, ks, v, coeffs, mesh, impl,
            dropout_rate=dropout_rate, dropout_rng=rng,
        )
    if use_flash(impl, dropout_rate, rng):
        if use_shard_flash(mesh):
            return shard_flash_multi_stream_attention(
                qs, ks, v, coeffs, mesh,
                dropout_rate=dropout_rate, dropout_rng=rng,
            )
        if flash_fn is not None:
            return flash_fn()
        return multi_stream_flash_attention(
            qs, ks, v, coeffs, dropout_rate=dropout_rate, dropout_rng=rng
        )
    return dense_fn()


# ---------------------------------------------------------------------------
# Blocks-layout conversion — the SINGLE definition of the two layouts:
# canonical (list of per-layer dicts, what init() builds and checkpoints
# store) vs layer-stacked (one dict whose leaves carry a leading n_layer
# axis, what the pipeline-parallel path shards P('pipeline')). Used by
# parallel/pipeline.py and train/checkpoint.py.


def stack_block_list(blocks: list, stack_fn=None) -> dict:
    """List of per-layer dicts -> one dict of layer-stacked leaves.
    ``stack_fn`` defaults to ``jnp.stack`` (pass ``np.stack`` for host-side
    conversion of device_get'd states)."""
    fn = jnp.stack if stack_fn is None else stack_fn
    return jax.tree_util.tree_map(lambda *xs: fn(list(xs), axis=0), *blocks)


def unstack_block_tree(blocks: dict, n_layer: int) -> list:
    """Inverse of :func:`stack_block_list`."""
    return [
        jax.tree_util.tree_map(lambda x: x[i], blocks) for i in range(n_layer)
    ]
