"""DiffTransformer: the 2-term differential attention model.

Functional JAX re-design of diff_transformer.py:128-185. Distinctive
reference behaviors preserved:
  - learned ABSOLUTE position embeddings — the only variant with a
    position table; no RoPE (diff_transformer.py:133-134, 157-159),
  - head_size = n_embd // (2 * n_head) with doubled values
    (diff_transformer.py:111, 30),
  - per-layer dynamic lambda_init with 1-BASED layer indices
    (diff_transformer.py:43, 161), computed purely from the static layer
    index instead of the reference's in-place buffer write,
  - full-width GroupLayerNorm over the head concat, then the CONSTANT 0.2
    output scale (diff_transformer.py:90-91; SURVEY.md section 2.1 quirks).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models import common
from differential_transformer_replication_tpu.ops import (
    causal_mask,
    diff_attention,
    diff_lambda,
    lambda_init_schedule,
)
from differential_transformer_replication_tpu.ops.lambdas import OUTPUT_SCALE
from differential_transformer_replication_tpu.ops.streams import diff_coeffs


# learned absolute positions, no RoPE (diff_transformer.py:133-134);
# consumers that precompute RoPE tables (parallel/pipeline.py) key on this.
USES_ROPE = False


def init(key: jax.Array, cfg: ModelConfig) -> dict:
    H, d, E = cfg.n_head, cfg.head_size, cfg.n_embd
    keys = jax.random.split(key, cfg.n_layer + 3)
    blocks = []
    for li in range(cfg.n_layer):
        kq, kk, kv, ko, kf = jax.random.split(keys[li], 5)
        blocks.append(
            {
                "ln1": common.layer_norm_params(E),
                "attn": {
                    # the two Q/K streams stacked on a leading axis
                    # (query1/query2, key1/key2: diff_transformer.py:26-29)
                    "wq": common.normal_init(kq, (2, E, H, d)),
                    "wk": common.normal_init(kk, (2, E, H, d)),
                    # doubled value projection (diff_transformer.py:30)
                    "wv": common.normal_init(kv, (E, H, 2 * d)),
                    # lambda vectors, zero-init (diff_transformer.py:35-38)
                    "lambda_q": jnp.zeros((2, H, d), jnp.float32),
                    "lambda_k": jnp.zeros((2, H, d), jnp.float32),
                    "gn": common.layer_norm_params(H * 2 * d),
                    # out-proj Linear(2*head_size*num_heads, n_embd), bias
                    # (diff_transformer.py:84)
                    "out": common.linear_params(ko, H * 2 * d, E),
                },
                "ln2": common.layer_norm_params(E),
                "ffn": common.ffn_params(kf, E),
            }
        )
    return {
        "tok_emb": common.normal_init(keys[-3], (cfg.vocab_size, E)),
        # learned absolute positions (diff_transformer.py:134)
        "pos_emb": common.normal_init(keys[-2], (cfg.block_size, E)),
        "blocks": blocks,
        "ln_f": common.layer_norm_params(E),
        "lm_head": common.linear_params(keys[-1], E, cfg.vocab_size),
    }


def _attn(
    x: jnp.ndarray,
    p: dict,
    layer_idx: int,
    mask: jnp.ndarray,
    dropout_rate: float,
    rng: Optional[jax.Array],
    impl: str = "xla",
    mesh=None,
    seq_impl: str = "ring",
    cfg=None,
) -> jnp.ndarray:
    B, T, E = x.shape
    r_att, r_out = common.split_rng(rng, 2)
    qs = jnp.einsum("bte,sehd->sbthd", x, p["wq"].astype(x.dtype))
    ks = jnp.einsum("bte,sehd->sbthd", x, p["wk"].astype(x.dtype))
    v = jnp.einsum("bte,ehd->bthd", x, p["wv"].astype(x.dtype))
    lam = diff_lambda(
        p["lambda_q"][0], p["lambda_k"][0],
        p["lambda_q"][1], p["lambda_k"][1],
        lambda_init_schedule(layer_idx),
    )  # (H,) fp32

    coeffs = diff_coeffs(lam)
    out = common.dispatch_attention(
        qs, ks, v, coeffs,
        # the dense XLA reference op (att1 - lam*att2, diff_transformer.py:70)
        lambda: diff_attention(
            qs[0], ks[0], qs[1], ks[1], v, lam,
            mask=mask, dropout_rate=dropout_rate, rng=r_att,
        ),
        impl=impl, mesh=mesh, dropout_rate=dropout_rate, rng=r_att,
        seq_impl=seq_impl,
        # kernel-native-layout fast path (the stacked projections above
        # are dead code on that branch and DCE'd)
        flash_fn=common.flash_bh_fn(
            x, p["wq"], p["wk"], p["wv"], coeffs,
            dropout_rate=dropout_rate, rng=r_att,
        ),
    )
    out = out.reshape(B, T, -1)  # concat heads (diff_transformer.py:89)
    out = common.apply_group_norm(out, p["gn"], cfg, mesh)  # :90
    out = out * OUTPUT_SCALE  # constant 0.2, :91
    out = common.linear(out, p["out"])
    return common.dropout(out, dropout_rate, r_out)


def embed(params: dict, idx: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Token embedding PLUS the learned absolute position table — the only
    family with one (diff_transformer.py:133-134, 157-159)."""
    T = idx.shape[-1]
    if T > cfg.block_size:
        # The reference raises (nn.Embedding index error) past block_size;
        # a JAX gather would silently clamp, so fail loudly instead.
        raise ValueError(f"sequence length {T} exceeds block_size {cfg.block_size}")
    tok = params["tok_emb"][idx]
    pos = params["pos_emb"][jnp.arange(T)]  # diff_transformer.py:158
    return (tok + pos).astype(jnp.dtype(cfg.compute_dtype))


def block_forward(
    x: jnp.ndarray,
    blk: dict,
    layer_idx,
    cfg: ModelConfig,
    cos: Optional[jnp.ndarray],
    sin: Optional[jnp.ndarray],
    mask: jnp.ndarray,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> jnp.ndarray:
    """One pre-LN residual block (diff_transformer.py:107-126).
    ``layer_idx`` is 1-based (diff_transformer.py:161) and may be a static
    int or a traced integer (the pipeline-parallel layer scan). ``cos``/
    ``sin`` are part of the uniform per-family signature; this family has
    no RoPE."""
    del cos, sin
    r_attn, r_ffn = common.split_rng(rng, 2)
    a = _attn(
        common.apply_pre_norm(x, blk["ln1"], cfg, mesh), blk["attn"],
        layer_idx, mask, cfg.dropout, r_attn, cfg.attention_impl, mesh,
        cfg.sequence_impl, cfg,
    )
    # residual add + ln2 + SwiGLU + down-proj + residual, ffn_impl-
    # dispatched (fused kernels when "pallas"; models/common.py)
    return common.apply_block_ffn(x, a, blk, cfg, r_ffn, mesh)


def forward(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    targets: Optional[jnp.ndarray] = None,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """(B, T) int tokens -> (logits (B, T, V), loss or None)."""
    B, T = idx.shape
    x = embed(params, idx, cfg)
    mask = causal_mask(T)
    rngs = common.split_rng(rng, cfg.n_layer)
    for li, (blk, r) in enumerate(zip(params["blocks"], rngs), 1):  # 1-based, :161
        fn = block_forward
        if cfg.remat:  # recompute this block's activations in the backward
            fn = common.remat_block(fn, cfg)  # cfg.remat_policy-aware
        x = fn(x, blk, li, cfg, None, None, mask, r, mesh)
    return common.tail_and_loss(x, params, cfg, targets, mesh)
