"""KV-cache incremental decoding.

The reference's ``generate`` recomputes the full O(T^2) forward for every
new token (control.py:163-171, diff_transformer.py:177-185,
Ndiff_transformer.py:232-241 — "no KV cache", SURVEY.md section 3.4).
``models/generate.py`` reproduces that behavior; this module is the
idiomatic-TPU upgrade: per-layer K/V caches make each new token O(T).

One chunked code path serves both phases — ``forward_chunk`` processes L
tokens starting at position ``pos`` against the cache, so prefill is a
single chunk at pos=0 and decoding is a chunk of length 1. All three
model families run through the shared multi-stream form (ops/streams.py):
per-stream K caches, per-stream softmax over the cached keys, coefficient
combine, then the family's post-attention stack (plain concat for
control; GroupLayerNorm + the constant 0.2 scale for diff/ndiff,
diff_transformer.py:90-91).

Family differences preserved (same citations as models/{control,diff,
ndiff}.py): control/ndiff rotate q/k with RoPE at absolute positions and
have no position table; diff adds its learned absolute position embedding
at the input instead. Generation is eval-mode: no dropout anywhere.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models.generate import sample_token
from differential_transformer_replication_tpu.models import common
from differential_transformer_replication_tpu.ops import (
    apply_rope,
    diff_lambda,
    group_layer_norm,
    lambda_init_schedule,
    ndiff_lambdas,
    ndiff_signs,
    rope_cos_sin,
)
from differential_transformer_replication_tpu.ops.lambdas import OUTPUT_SCALE
from differential_transformer_replication_tpu.ops.streams import (
    NEG_INF,
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)


def _n_streams(cfg: ModelConfig) -> int:
    return {"control": 1, "diff": 2, "ndiff": cfg.n_terms}[cfg.model]


def _uses_rope(cfg: ModelConfig) -> bool:
    return cfg.model in ("control", "ndiff")


def init_cache(cfg: ModelConfig, batch_size: int) -> list:
    """Per-layer K/V buffers sized to ``block_size``: K is per-stream
    (S, B, M, H, d); V is shared across streams (B, M, H, dv)."""
    S = _n_streams(cfg)
    H, d, dv, M = cfg.n_head, cfg.head_size, cfg.value_size, cfg.block_size
    dt = jnp.dtype(cfg.compute_dtype)
    return [
        {
            "k": jnp.zeros((S, batch_size, M, H, d), dt),
            "v": jnp.zeros((batch_size, M, H, dv), dt),
        }
        for _ in range(cfg.n_layer)
    ]


def _stacked_wq(p_attn: dict) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Normalize the per-family weight layouts to stacked (S, E, H, d)."""
    wq, wk = p_attn["wq"], p_attn["wk"]
    if wq.ndim == 3:  # control: (E, H, d)
        wq, wk = wq[None], wk[None]
    return wq, wk


def _layer_coeffs(cfg: ModelConfig, p_attn: dict, layer_idx: int) -> jnp.ndarray:
    """(S, H) combine coefficients for this layer (1-based layer_idx for
    the dynamic lambda_init schedule, diff_transformer.py:43,161)."""
    if cfg.model == "control":
        return vanilla_coeffs(cfg.n_head)
    if cfg.model == "diff":
        lam = diff_lambda(
            p_attn["lambda_q"][0], p_attn["lambda_k"][0],
            p_attn["lambda_q"][1], p_attn["lambda_k"][1],
            lambda_init_schedule(layer_idx),
        )
        return diff_coeffs(lam)
    lams = ndiff_lambdas(
        p_attn["lambda_q"], p_attn["lambda_k"], lambda_init_schedule(layer_idx)
    )
    return ndiff_coeffs(lams, ndiff_signs(cfg.n_terms))


def _attn_chunk(
    x: jnp.ndarray,  # (B, L, E) normed input chunk
    p_attn: dict,
    layer_cache: dict,
    pos,  # scalar int: absolute position of the chunk start
    layer_idx: int,
    cfg: ModelConfig,
    cos: jnp.ndarray,  # (L, d/2) tables pre-sliced at [pos, pos+L)
    sin: jnp.ndarray,
) -> Tuple[jnp.ndarray, dict]:
    B, L, E = x.shape
    M = cfg.block_size
    wq, wk = _stacked_wq(p_attn)
    qs = jnp.einsum("ble,sehd->sblhd", x, wq.astype(x.dtype))
    ks = jnp.einsum("ble,sehd->sblhd", x, wk.astype(x.dtype))
    v = jnp.einsum("ble,ehd->blhd", x, p_attn["wv"].astype(x.dtype))
    if _uses_rope(cfg):
        qs = apply_rope(qs, cos, sin)
        ks = apply_rope(ks, cos, sin)

    k_cache = jax.lax.dynamic_update_slice(
        layer_cache["k"], ks, (0, 0, pos, 0, 0)
    )
    v_cache = jax.lax.dynamic_update_slice(layer_cache["v"], v, (0, pos, 0, 0))

    scale = 1.0 / (cfg.head_size ** 0.5)
    scores = (
        jnp.einsum("sblhd,sbmhd->sbhlm", qs, k_cache).astype(jnp.float32) * scale
    )
    # causal over absolute positions: chunk row l sits at pos+l and may see
    # cached columns m <= pos+l (later cache slots are zeros — masked off)
    rows = pos + jnp.arange(L)[:, None]
    cols = jnp.arange(M)[None, :]
    scores = jnp.where((cols <= rows)[None, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)  # per-stream, fp32

    coeffs = _layer_coeffs(cfg, p_attn, layer_idx)  # (S, H)
    combined = jnp.einsum("sh,sbhlm->bhlm", coeffs, probs)
    out = jnp.einsum("bhlm,bmhe->blhe", combined.astype(v.dtype), v_cache)
    out = out.reshape(B, L, -1)  # concat heads
    if cfg.model in ("diff", "ndiff"):
        out = group_layer_norm(out, p_attn["gn"]["w"], p_attn["gn"]["b"])
        out = out * OUTPUT_SCALE  # constant 0.2 (diff_transformer.py:91)
    out = common.linear(out, p_attn["out"])
    return out, {"k": k_cache, "v": v_cache}


def forward_chunk(
    params: dict,
    tokens: jnp.ndarray,  # (B, L) at absolute positions [pos, pos+L)
    pos,
    cache: list,
    cfg: ModelConfig,
) -> Tuple[jnp.ndarray, list]:
    """Process a chunk against the cache. Returns ((B, L, V) logits,
    updated cache). Prefill = one big chunk at pos=0; decode = L=1.

    ``pos + L`` must not exceed ``block_size`` — past it,
    dynamic_update_slice would silently clamp the cache write and corrupt
    the last slot, so concrete positions fail loudly here (the repo's
    fail-loudly convention, models/diff.py forward). Traced positions
    cannot be checked at trace time; jitted callers must guard like
    generate_cached does."""
    B, L = tokens.shape
    if isinstance(pos, (int,)) and pos + L > cfg.block_size:
        raise ValueError(
            f"chunk [{pos}, {pos + L}) exceeds block_size {cfg.block_size}: "
            "the cache write would clamp and corrupt the last slot"
        )
    compute = jnp.dtype(cfg.compute_dtype)
    x = params["tok_emb"][tokens].astype(compute)
    if cfg.model == "diff":  # learned absolute positions (diff_transformer.py:158)
        x = x + jax.lax.dynamic_slice_in_dim(
            params["pos_emb"], pos, L, axis=0
        ).astype(compute)
        cos = sin = None
    else:
        cos_full, sin_full = rope_cos_sin(cfg.head_size, cfg.block_size)
        cos = jax.lax.dynamic_slice_in_dim(cos_full, pos, L, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(sin_full, pos, L, axis=0)

    new_cache = []
    for li, blk in enumerate(params["blocks"], 1):  # 1-based (diff_transformer.py:161)
        a, layer_cache = _attn_chunk(
            common.apply_layer_norm(x, blk["ln1"]), blk["attn"],
            cache[li - 1], pos, li, cfg, cos, sin,
        )
        x = x + a
        x = x + common.apply_ffn(common.apply_layer_norm(x, blk["ln2"]), blk["ffn"])
        new_cache.append(layer_cache)
    x = common.apply_layer_norm(x, params["ln_f"])
    logits = common.linear(x, params["lm_head"])
    return logits, new_cache


@partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k")
)
def generate_cached(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    max_new_tokens: int,
    rng: jax.Array,
    temperature: float = 1.0,
    top_k=None,
) -> jnp.ndarray:
    """KV-cached counterpart of models/generate.py: same sampling contract
    (temperature-1 categorical over the last position, prompt included in
    the return), O(T) per new token instead of O(T^2).

    Requires ``T0 + max_new_tokens <= block_size`` (no sliding-window
    support — use models/generate.py past the context limit, which
    reproduces the reference's crop behavior)."""
    B, T0 = idx.shape
    if T0 + max_new_tokens > cfg.block_size:
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"block_size ({cfg.block_size}); use models.generate for the "
            "sliding-window behavior"
        )
    cache = init_cache(cfg, B)
    logits, cache = forward_chunk(params, idx, 0, cache, cfg)
    samples = jnp.zeros((B, max_new_tokens), idx.dtype)

    rng, key0 = jax.random.split(rng)
    first = sample_token(
        key0, logits[:, -1, :].astype(jnp.float32), temperature, top_k
    ).astype(idx.dtype)
    samples = samples.at[:, 0].set(first)

    def body(i, carry):
        cache, samples, rng = carry
        rng, key = jax.random.split(rng)
        prev = samples[:, i - 1]
        logits, cache = forward_chunk(
            params, prev[:, None], T0 + i - 1, cache, cfg
        )
        nxt = sample_token(
            key, logits[:, -1, :].astype(jnp.float32), temperature, top_k
        ).astype(samples.dtype)
        samples = samples.at[:, i].set(nxt)
        return cache, samples, rng

    if max_new_tokens > 1:
        _, samples, _ = jax.lax.fori_loop(
            1, max_new_tokens, body, (cache, samples, rng)
        )
    return jnp.concatenate([idx, samples], axis=1)
