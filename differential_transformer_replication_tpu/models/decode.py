"""KV-cache incremental decoding.

The reference's ``generate`` recomputes the full O(T^2) forward for every
new token (control.py:163-171, diff_transformer.py:177-185,
Ndiff_transformer.py:232-241 — "no KV cache", SURVEY.md section 3.4).
``models/generate.py`` reproduces that behavior; this module is the
idiomatic-TPU upgrade: per-layer K/V caches make each new token O(T).
The cache is a RING over block_size slots, so the RoPE families
(control/ndiff) keep the O(T)/token fast path arbitrarily far PAST
block_size: each step attends over exactly the last block_size keys
(RoPE scores depend only on relative positions, so absolute-position
rotation needs no re-rotating as the window rolls). Past the boundary
this is SLIDING-WINDOW ATTENTION — the standard KV-cached long-decode
semantics — NOT a bit-reproduction of the reference's crop
(control.py:163-171), and no O(T)/token scheme can be one for depth
>= 2: the reference recomputes the whole cropped forward each step, so
when the window slides, EVERY remaining position loses its oldest
visible key and all its deep-layer activations change — Omega(M^2)
recompute per token is inherent to crop semantics. The ring instead
keeps each cached activation as computed with its own full window
(receptive field grows with depth, strictly containing the crop's).
The two are exactly equal for single-layer models and everywhere up to
the block boundary (tests/test_decode.py pins both). The diff family's
learned absolute position table cannot roll at all (each window slide
would re-embed every cached position), so it keeps the hard in-window
bound and the windowed ``generate`` beyond it.

One chunked code path serves both phases — ``forward_chunk`` processes L
tokens starting at position ``pos`` against the cache, so prefill is a
single chunk at pos=0 and decoding is a chunk of length 1. All three
model families run through the shared multi-stream form (ops/streams.py):
per-stream K caches, per-stream softmax over the cached keys, coefficient
combine, then the family's post-attention stack (plain concat for
control; GroupLayerNorm + the constant 0.2 scale for diff/ndiff,
diff_transformer.py:90-91).

Family differences preserved (same citations as models/{control,diff,
ndiff}.py): control/ndiff rotate q/k with RoPE at absolute positions and
have no position table; diff adds its learned absolute position embedding
at the input instead. Generation is eval-mode: no dropout anywhere.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models.generate import sample_token
from differential_transformer_replication_tpu.models import common
from differential_transformer_replication_tpu.ops import (
    apply_rope,
    diff_lambda,
    lambda_init_schedule,
    ndiff_lambdas,
    ndiff_signs,
    rope_cos_sin,
)
from differential_transformer_replication_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_multi,
    decode_attention_multi_paged,
    decode_attention_multi_reference,
    decode_attention_paged,
    decode_attention_reference,
    dequantize_kv,
    quantize_kv,
)
from differential_transformer_replication_tpu.ops.lambdas import OUTPUT_SCALE
from differential_transformer_replication_tpu.ops.streams import (
    NEG_INF,
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)


def _n_streams(cfg: ModelConfig) -> int:
    return {"control": 1, "diff": 2, "ndiff": cfg.n_terms}[cfg.model]


def _uses_rope(cfg: ModelConfig) -> bool:
    return cfg.model in ("control", "ndiff")


# Pool-batch axis of each cache leaf: K (and its scales) carry the
# stream axis first, V does not. The single source of truth for every
# per-slot slice/scatter/merge over the cache pytree (serving/engine.py).
KV_CACHE_BATCH_AXIS = {"k": 1, "v": 0, "k_scale": 1, "v_scale": 0}


def kv_store_dtype(cfg: ModelConfig) -> str:
    """Resolved KV-cache storage dtype: ``"int8"`` or a float dtype
    string (``kv_cache_dtype == "auto"`` stores ``compute_dtype``, the
    pre-quantization behavior)."""
    if cfg.kv_cache_dtype == "int8":
        return "int8"
    if cfg.kv_cache_dtype == "bf16":
        return "bfloat16"
    return cfg.compute_dtype


def apply_logit_pipeline(logits: jnp.ndarray, allowed: jnp.ndarray,
                         counts: jnp.ndarray, rep: jnp.ndarray,
                         pres: jnp.ndarray,
                         freq: jnp.ndarray) -> jnp.ndarray:
    """The per-row logit-processor pipeline of the serving engine's
    structured-decoding subsystem (serving/constrain.py): repetition /
    presence / frequency penalties over the request's generated-token
    histogram, then the constraint mask. ONE definition shared by the
    L=1 pool sampler and the fused spec-verify accept step
    (serving/engine.py) — the Leviathan accept/reject test preserves
    the target distribution only if drafter proposals and verify rows
    see IDENTICAL logit processing, and greedy constrained+spec
    bit-parity needs the same argmax surface in both formulations.

    ``logits`` (B, V) float; ``allowed`` (B, V) bool constraint mask
    (all-ones for unconstrained rows); ``counts`` (B, V) int32
    occurrence histogram of the row's generated tokens; ``rep`` /
    ``pres`` / ``freq`` (B,) float penalties (1.0 / 0.0 / 0.0 = off).
    Rows with every penalty off and an all-ones mask pass through
    BIT-IDENTICAL (a ``where`` selects the raw logits), so the
    pre-pipeline sampler's outputs — and every pinned bit-repro test —
    are unchanged for unconstrained traffic. Applied BEFORE top-k and
    temperature: the threshold and the draw both see the processed
    surface.
    """
    seen = counts > 0
    cf = counts.astype(logits.dtype)
    # GPT-style repetition penalty: shrink positive logits, push
    # negative ones further down, for every already-generated token
    r = rep[:, None]
    penalized = jnp.where(
        seen,
        jnp.where(logits > 0, logits / r, logits * r),
        logits,
    )
    penalized = (
        penalized
        - pres[:, None] * seen.astype(logits.dtype)
        - freq[:, None] * cf
    )
    inactive = (rep == 1.0) & (pres == 0.0) & (freq == 0.0)
    x = jnp.where(inactive[:, None], logits, penalized)
    return jnp.where(allowed, x, -jnp.inf)


def quality_vector(lp: jnp.ndarray, proc: jnp.ndarray,
                   tokens: jnp.ndarray,
                   prev: jnp.ndarray,
                   top2: jnp.ndarray = None) -> jnp.ndarray:
    """Fixed-shape per-slot quality vector, computed INSIDE the jitted
    sample/verify step (obs/quality.py is the host-side consumer):

      [..., 0] sampled-distribution entropy in nats — over ``lp``, the
               log-softmax of the distribution actually drawn from
               (penalties + constraint mask + top-k + temperature all
               applied), so a collapsing or flattening model moves it
               immediately;
      [..., 1] top-1 logit margin on the processed surface ``proc``
               (pre-top-k/temperature): the argmax's confidence gap,
               the signal spec-verify acceptance already keys on;
      [..., 2] repetition flag — sampled token equals the previous
               emitted token (``prev < 0`` = no previous token); the
               engine accumulates the host-side run length from it.

    Shapes: ``lp``/``proc`` (..., V), ``tokens``/``prev`` (...) int32;
    returns (..., 3) float32. Runtime arrays only — no shape depends
    on request state, so inactive slots pass through and the decode
    compile count stays pinned. ``top2``, when given, is the caller's
    already-computed two largest PROCESSED logits (..., >=2) — the
    samplers have a descending sort of ``proc`` on hand for the top-k
    threshold, and reusing its head keeps the tail out of a second
    full top_k (which breaks XLA's sampler fusion and dominates the
    telemetry cost on small models). NaN-degradation contract:
    fully-masked rows give entropy 0 over the -inf mass (``where``
    keeps the 0*inf NaN out) and an infinite margin; genuinely
    non-finite logits propagate as non-finite values the host treats
    as "no signal" (never a crash — that guard is the sampler's
    finite-ok column).
    """
    finite = jnp.isfinite(lp)
    plogp = jnp.where(finite, jnp.exp(lp) * lp, 0.0)
    entropy = -jnp.sum(plogp, axis=-1)
    if top2 is None and proc.shape[-1] >= 2:
        top2 = jax.lax.top_k(proc, 2)[0]
    if top2 is not None:
        margin = top2[..., 0] - top2[..., 1]
    else:  # degenerate single-token vocab: no runner-up to compare
        margin = jnp.zeros(proc.shape[:-1], proc.dtype)
    repeat = ((tokens == prev) & (prev >= 0))
    return jnp.stack([
        entropy.astype(jnp.float32),
        margin.astype(jnp.float32),
        repeat.astype(jnp.float32),
    ], axis=-1)


def init_cache(cfg: ModelConfig, batch_size: int) -> list:
    """Per-layer K/V buffers sized to ``block_size``, HEAD-MAJOR so the
    per-(slot, head) ring is contiguous — the fused decode kernel's
    native layout (ops/decode_attention.py) and an equivalent einsum for
    the XLA chunk path: K is per-stream (S, B, H, M, d); V is shared
    across streams (B, H, M, dv).

    ``cfg.kv_cache_dtype == "int8"`` stores symmetric per-head-scale
    int8 values plus fp32 scales (``k_scale`` (S, B, H, M) / ``v_scale``
    (B, H, M)) — about half the bf16 bytes per slot; otherwise the
    resolved float dtype (:func:`kv_store_dtype`)."""
    S = _n_streams(cfg)
    H, d, dv, M = cfg.n_head, cfg.head_size, cfg.value_size, cfg.block_size
    store = kv_store_dtype(cfg)
    cache = []
    for _ in range(cfg.n_layer):
        if store == "int8":
            layer = {
                "k": jnp.zeros((S, batch_size, H, M, d), jnp.int8),
                "v": jnp.zeros((batch_size, H, M, dv), jnp.int8),
                "k_scale": jnp.zeros((S, batch_size, H, M), jnp.float32),
                "v_scale": jnp.zeros((batch_size, H, M), jnp.float32),
            }
        else:
            dt = jnp.dtype(store)
            layer = {
                "k": jnp.zeros((S, batch_size, H, M, d), dt),
                "v": jnp.zeros((batch_size, H, M, dv), dt),
            }
        cache.append(layer)
    return cache


def _dequant_layer(layer_cache: dict, dtype):
    """The layer's (K, V) as float arrays in ``dtype``: a cast-free read
    on the float path, a fused multiply on the int8 path (the Pallas
    kernel instead dequantizes inside its tile loads)."""
    if "k_scale" in layer_cache:
        return (
            dequantize_kv(layer_cache["k"], layer_cache["k_scale"], dtype),
            dequantize_kv(layer_cache["v"], layer_cache["v_scale"], dtype),
        )
    return layer_cache["k"], layer_cache["v"]


def _write_chunk(layer_cache: dict, ks: jnp.ndarray, v: jnp.ndarray,
                 slot) -> dict:
    """Write one chunk's new K/V — ks (S, B, L, H, d), v (B, L, H, dv) —
    into the ring at ``slot``, quantizing on the int8 path so the chunk's
    own attention (and every later step) reads exactly what the cache
    holds."""
    k_new = ks.transpose(0, 1, 3, 2, 4)  # (S, B, H, L, d)
    v_new = v.transpose(0, 2, 1, 3)  # (B, H, L, dv)
    out = dict(layer_cache)
    if "k_scale" in layer_cache:
        kq, ksc = quantize_kv(k_new)
        vq, vsc = quantize_kv(v_new)
        out["k"] = jax.lax.dynamic_update_slice(
            layer_cache["k"], kq, (0, 0, 0, slot, 0)
        )
        out["k_scale"] = jax.lax.dynamic_update_slice(
            layer_cache["k_scale"], ksc, (0, 0, 0, slot)
        )
        out["v"] = jax.lax.dynamic_update_slice(
            layer_cache["v"], vq, (0, 0, slot, 0)
        )
        out["v_scale"] = jax.lax.dynamic_update_slice(
            layer_cache["v_scale"], vsc, (0, 0, slot)
        )
    else:
        dt = layer_cache["k"].dtype
        out["k"] = jax.lax.dynamic_update_slice(
            layer_cache["k"], k_new.astype(dt), (0, 0, 0, slot, 0)
        )
        out["v"] = jax.lax.dynamic_update_slice(
            layer_cache["v"], v_new.astype(dt), (0, 0, slot, 0)
        )
    return out


def _stacked_wq(p_attn: dict) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Normalize the per-family weight layouts to stacked (S, E, H, d)."""
    wq, wk = p_attn["wq"], p_attn["wk"]
    if wq.ndim == 3:  # control: (E, H, d)
        wq, wk = wq[None], wk[None]
    return wq, wk


def _layer_coeffs(cfg: ModelConfig, p_attn: dict, layer_idx: int) -> jnp.ndarray:
    """(S, H) combine coefficients for this layer (1-based layer_idx for
    the dynamic lambda_init schedule, diff_transformer.py:43,161)."""
    if cfg.model == "control":
        return vanilla_coeffs(cfg.n_head)
    if cfg.model == "diff":
        lam = diff_lambda(
            p_attn["lambda_q"][0], p_attn["lambda_k"][0],
            p_attn["lambda_q"][1], p_attn["lambda_k"][1],
            lambda_init_schedule(layer_idx),
        )
        return diff_coeffs(lam)
    lams = ndiff_lambdas(
        p_attn["lambda_q"], p_attn["lambda_k"], lambda_init_schedule(layer_idx)
    )
    return ndiff_coeffs(lams, ndiff_signs(cfg.n_terms))


def _attn_chunk(
    x: jnp.ndarray,  # (B, L, E) normed input chunk
    p_attn: dict,
    layer_cache: dict,
    pos,  # scalar int: absolute position of the chunk start
    layer_idx: int,
    cfg: ModelConfig,
    cos: jnp.ndarray,  # (L, d/2) tables pre-sliced at [pos, pos+L)
    sin: jnp.ndarray,
    window: int = 0,  # visibility clip; 0/None = the cache size M
) -> Tuple[jnp.ndarray, dict]:
    B, L, E = x.shape
    M = cfg.block_size
    W = int(window) if window else M
    wq, wk = _stacked_wq(p_attn)
    qs = jnp.einsum("ble,sehd->sblhd", x, wq.astype(x.dtype))
    ks = jnp.einsum("ble,sehd->sblhd", x, wk.astype(x.dtype))
    v = jnp.einsum("ble,ehd->blhd", x, p_attn["wv"].astype(x.dtype))
    if _uses_rope(cfg):
        qs = apply_rope(qs, cos, sin)
        ks = apply_rope(ks, cos, sin)

    # RING cache: slot = pos mod M, so positions past block_size roll over
    # the oldest entries instead of clamping. Keys are rotated at their
    # ABSOLUTE position; RoPE scores depend only on (q_pos - k_pos), so
    # the rolled window needs no re-rotating (sliding-window attention —
    # see the module docstring for how this relates to the reference's
    # crop semantics). The write quantizes on the int8 path, so the
    # chunk's own attention below reads exactly what later decode steps
    # will read.
    slot = jax.lax.rem(jnp.asarray(pos, jnp.int32), M)
    new_cache = _write_chunk(layer_cache, ks, v, slot)
    k_cache, v_cache = _dequant_layer(new_cache, x.dtype)

    scale = 1.0 / (cfg.head_size ** 0.5)
    scores = (
        jnp.einsum("sblhd,sbhmd->sbhlm", qs, k_cache).astype(jnp.float32) * scale
    )
    # Ring-aware causal mask over absolute positions. After this chunk's
    # write the latest absolute position is ``last``; slot m then holds
    # absolute position ``last - ((last - m) mod M)`` (the most recent
    # write to that slot; negative = never written). Chunk row l sits at
    # absolute pos+l and may see a slot iff its held position is in the
    # sliding window [row - W + 1, row] — which also masks same-chunk
    # future rows and unwritten (zero) slots. W < M (an explicit
    # ``window``) clips visibility tighter than the cache — used by the
    # append-oracle test to validate the ring arithmetic.
    rows = pos + jnp.arange(L)[:, None]
    slots = jnp.arange(M)[None, :]
    last = pos + L - 1
    held = last - jax.lax.rem(
        jnp.asarray(last, jnp.int32) - slots, jnp.asarray(M, jnp.int32)
    )
    visible = (held <= rows) & (held >= 0) & (held > rows - W)
    scores = jnp.where(visible[None, None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)  # per-stream, fp32

    coeffs = _layer_coeffs(cfg, p_attn, layer_idx)  # (S, H)
    combined = jnp.einsum("sh,sbhlm->bhlm", coeffs, probs)
    out = jnp.einsum("bhlm,bhme->blhe", combined.astype(v.dtype), v_cache)
    out = out.reshape(B, L, -1)  # concat heads
    if cfg.model in ("diff", "ndiff"):
        out = common.apply_group_norm(out, p_attn["gn"], cfg)
        out = out * OUTPUT_SCALE  # constant 0.2 (diff_transformer.py:91)
    out = common.linear(out, p_attn["out"])
    return out, new_cache


def forward_chunk(
    params: dict,
    tokens: jnp.ndarray,  # (B, L) at absolute positions [pos, pos+L)
    pos,
    cache: list,
    cfg: ModelConfig,
    rope_len: int = 0,
    window: int = 0,
) -> Tuple[jnp.ndarray, list]:
    """Process a chunk against the cache. Returns ((B, L, V) logits,
    updated cache). Prefill = one big chunk at pos=0; decode = L=1.

    The cache is a RING over ``block_size`` slots, so RoPE families
    (control/ndiff) may run ``pos`` past block_size indefinitely — the
    oldest keys roll off at O(T) per token (sliding-window attention;
    the module docstring relates this to the reference's crop,
    control.py:163-171). ``rope_len`` sizes the rotation tables
    (>= pos + L; defaults to block_size for the in-window case);
    ``window`` optionally clips visibility tighter than the cache size
    (test/oracle use). The DIFF family's learned absolute position
    table (diff_transformer.py:158) makes cached reuse past block_size
    architecturally impossible — every cached K/V would need
    recomputing under the shifted position embeddings — so concrete
    positions fail loudly there (the repo's fail-loud convention) and
    models/generate.py remains its sliding-window path. Other
    concrete-position chunks that cannot be represented also fail
    loudly: RoPE positions past the table (pass a bigger ``rope_len``),
    multi-token chunks at rolled positions (their in-chunk writes would
    evict keys still visible to earlier rows), and writes wrapping the
    ring slice boundary."""
    B, L = tokens.shape
    M = cfg.block_size
    if isinstance(pos, int):
        if cfg.model == "diff" and pos + L > M:
            raise ValueError(
                f"chunk [{pos}, {pos + L}) exceeds block_size {M}: the diff "
                "family's learned absolute position table cannot roll (each "
                "slide would re-embed every cached position); use "
                "models.generate for its sliding-window behavior"
            )
        if cfg.model != "diff" and pos + L > max(int(rope_len), M):
            raise ValueError(
                f"chunk [{pos}, {pos + L}) exceeds the RoPE table length "
                f"{max(int(rope_len), M)}: pass rope_len >= the final "
                "position or the cos/sin slice would silently clamp and "
                "mis-rotate"
            )
        if pos >= M and L > 1:
            raise ValueError(
                f"multi-token chunk at rolled position {pos} >= block_size "
                f"{M}: its in-chunk writes would evict keys still inside "
                "earlier rows' sliding windows (silently shrinking their "
                "attention); feed rolled positions one token at a time"
            )
        if (pos % M) + L > M:
            raise ValueError(
                f"chunk [{pos}, {pos + L}) wraps the ring boundary (slot "
                f"{pos % M} + {L} > {M}): split it at the boundary"
            )
    compute = jnp.dtype(cfg.compute_dtype)
    x = params["tok_emb"][tokens].astype(compute)
    if cfg.model == "diff":  # learned absolute positions (diff_transformer.py:158)
        x = x + jax.lax.dynamic_slice_in_dim(
            params["pos_emb"], pos, L, axis=0
        ).astype(compute)
        cos = sin = None
    else:
        cos_full, sin_full = rope_cos_sin(
            cfg.head_size, max(int(rope_len), M)
        )
        cos = jax.lax.dynamic_slice_in_dim(cos_full, pos, L, axis=0)
        sin = jax.lax.dynamic_slice_in_dim(sin_full, pos, L, axis=0)

    new_cache = []
    for li, blk in enumerate(params["blocks"], 1):  # 1-based (diff_transformer.py:161)
        a, layer_cache = _attn_chunk(
            common.apply_pre_norm(x, blk["ln1"], cfg), blk["attn"],
            cache[li - 1], pos, li, cfg, cos, sin, window=window,
        )
        # residual add + ln2 + SwiGLU + down-proj + residual — the same
        # ffn_impl dispatch as the training blocks (dropout-free here:
        # generation is eval-mode)
        x = common.apply_block_ffn(x, a, blk, cfg)
        new_cache.append(layer_cache)
    x = common.apply_pre_norm(x, params["ln_f"], cfg)
    logits = common.linear(x, params["lm_head"])
    return logits, new_cache


# ---------------------------------------------------------------------------
# Pool-native batched decode (decode_attention_impl == "pallas"): the
# whole slot pool advances one token in ONE call — no vmap over rows —
# with each row at its own absolute position and attention running
# through the fused Pallas kernel (ops/decode_attention.py). The XLA
# baseline keeps the per-row vmapped forward_chunk path untouched.
# ---------------------------------------------------------------------------


def _rope_rows(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray):
    """Rotate single-token streams at PER-ROW positions: x (S, B, H, d),
    cos/sin (B, d/2) gathered at each row's own position. Same fp32
    even/odd-lane formula as ops/rope.py:apply_rope (which slices one
    shared [0, T) table and so cannot express per-row positions)."""
    xf = x.astype(jnp.float32)
    x_even = xf[..., 0::2]
    x_odd = xf[..., 1::2]
    c = cos[None, :, None, :]  # broadcast over (S, ..., H, ...)
    s = sin[None, :, None, :]
    rot_even = x_even * c - x_odd * s
    rot_odd = x_even * s + x_odd * c
    return jnp.stack([rot_even, rot_odd], axis=-1).reshape(x.shape).astype(
        x.dtype
    )


def _update_cache_rows(layer_cache: dict, ks: jnp.ndarray, v: jnp.ndarray,
                       pos: jnp.ndarray, M: int) -> dict:
    """Scatter each row's new K/V — ks (S, B, H, d), v (B, H, dv) — into
    its own ring slot ``pos[b] % M`` (one XLA scatter per leaf; row/slot
    pairs are unique so the update order is immaterial)."""
    slot = jax.lax.rem(jnp.asarray(pos, jnp.int32), M)
    b_idx = jnp.arange(slot.shape[0])
    out = dict(layer_cache)
    if "k_scale" in layer_cache:
        kq, ksc = quantize_kv(ks)
        vq, vsc = quantize_kv(v)
        out["k"] = layer_cache["k"].at[:, b_idx, :, slot].set(
            kq.transpose(1, 0, 2, 3)
        )
        out["k_scale"] = layer_cache["k_scale"].at[:, b_idx, :, slot].set(
            ksc.transpose(1, 0, 2)
        )
        out["v"] = layer_cache["v"].at[b_idx, :, slot].set(vq)
        out["v_scale"] = layer_cache["v_scale"].at[b_idx, :, slot].set(vsc)
    else:
        dt = layer_cache["k"].dtype
        out["k"] = layer_cache["k"].at[:, b_idx, :, slot].set(
            ks.astype(dt).transpose(1, 0, 2, 3)
        )
        out["v"] = layer_cache["v"].at[b_idx, :, slot].set(v.astype(dt))
    return out


def _pool_attn(
    x: jnp.ndarray,  # (B, E) normed single-token inputs, one per slot
    p_attn: dict,
    layer_cache: dict,
    pos: jnp.ndarray,  # (B,) int32 absolute positions
    layer_idx: int,
    cfg: ModelConfig,
    cos,  # (B, d/2) per-row RoPE tables (None for the diff family)
    sin,
):
    """The batched L=1 twin of :func:`_attn_chunk`: update-then-attend
    over every slot row at once, attention dispatched on
    ``cfg.decode_attention_impl``."""
    B = x.shape[0]
    wq, wk = _stacked_wq(p_attn)
    qs = jnp.einsum("be,sehd->sbhd", x, wq.astype(x.dtype))
    ks = jnp.einsum("be,sehd->sbhd", x, wk.astype(x.dtype))
    v = jnp.einsum("be,ehd->bhd", x, p_attn["wv"].astype(x.dtype))
    if _uses_rope(cfg):
        qs = _rope_rows(qs, cos, sin)
        ks = _rope_rows(ks, cos, sin)
    new_cache = _update_cache_rows(layer_cache, ks, v, pos, cfg.block_size)
    coeffs = _layer_coeffs(cfg, p_attn, layer_idx)
    if cfg.decode_attention_impl == "pallas":
        out = decode_attention(
            qs, new_cache["k"], new_cache["v"], pos, coeffs,
            k_scale=new_cache.get("k_scale"),
            v_scale=new_cache.get("v_scale"),
        )
    else:
        k_eff, v_eff = _dequant_layer(new_cache, x.dtype)
        out = decode_attention_reference(qs, k_eff, v_eff, pos, coeffs)
    out = out.reshape(B, -1)  # concat heads
    if cfg.model in ("diff", "ndiff"):
        out = common.apply_group_norm(out, p_attn["gn"], cfg)
        out = out * OUTPUT_SCALE
    return common.linear(out, p_attn["out"]), new_cache


def forward_decode_pool(
    params: dict,
    tokens: jnp.ndarray,  # (B,) current token per slot row
    pos,  # (B,) int32 absolute position per row (runtime array)
    cache: list,
    cfg: ModelConfig,
    rope_len: int = 0,
) -> Tuple[jnp.ndarray, list]:
    """Advance the WHOLE slot pool by one token: returns ((B, V) logits,
    updated cache). The batched counterpart of a length-1
    :func:`forward_chunk` per row — same ring semantics, same
    update-then-attend order, every row at its own position — minus the
    vmap, so the fused decode kernel sees the full pool in one
    ``(B*H,)``-grid call per layer. Host-side admission guards
    (serving/engine.py submit, generate_cached's checks) own the
    concrete-position validity rules; everything here is traced."""
    B = tokens.shape[0]
    M = cfg.block_size
    compute = jnp.dtype(cfg.compute_dtype)
    pos = jnp.asarray(pos, jnp.int32)
    x = params["tok_emb"][tokens].astype(compute)  # (B, E)
    cos = sin = None
    if cfg.model == "diff":
        x = x + params["pos_emb"][pos].astype(compute)
    else:
        cos_full, sin_full = rope_cos_sin(
            cfg.head_size, max(int(rope_len), M)
        )
        cos = cos_full[pos]  # (B, d/2) at each row's own position
        sin = sin_full[pos]
    new_cache = []
    for li, blk in enumerate(params["blocks"], 1):  # 1-based schedule
        a, layer_cache = _pool_attn(
            common.apply_pre_norm(x, blk["ln1"], cfg), blk["attn"],
            cache[li - 1], pos, li, cfg, cos, sin,
        )
        x = common.apply_block_ffn(x, a, blk, cfg)
        new_cache.append(layer_cache)
    x = common.apply_pre_norm(x, params["ln_f"], cfg)
    return common.linear(x, params["lm_head"]), new_cache


def merge_cache_update(active: jnp.ndarray, new_cache: list,
                       old_cache: list) -> list:
    """Masked cache merge over the pool-batch axis of every leaf: rows
    where ``active`` keep the update, others keep their old buffers —
    how the engine's batched decode step discards the garbage writes of
    inactive/mid-prefill slots (serving/engine.py)."""
    merged = []
    for nc, oc in zip(new_cache, old_cache):
        layer = {}
        for key in nc:
            axis = KV_CACHE_BATCH_AXIS[key]
            shape = (1,) * axis + (-1,) + (1,) * (nc[key].ndim - axis - 1)
            layer[key] = jnp.where(active.reshape(shape), nc[key], oc[key])
        merged.append(layer)
    return merged


# ---------------------------------------------------------------------------
# Paged KV cache (serving/pages.py): the pool's batch axis indexes
# PHYSICAL PAGES of page_size tokens instead of whole slots. A slot's
# logical block_size ring maps onto pages through a per-slot page-table
# row (runtime int32 arrays — allocation/free/sharing never recompiles).
# Physical page 0 is the reserved trash page: unallocated logical pages
# and inactive rows' decode writes land there, so the jitted step needs
# no masking. KV_CACHE_BATCH_AXIS doubles as the page-axis table: the
# page axis sits exactly where the slot axis sat.
# ---------------------------------------------------------------------------


def init_cache_paged(cfg: ModelConfig, num_pages: int,
                     page_size: int) -> list:
    """Per-layer paged K/V pools: the :func:`init_cache` layout with
    ``(num_pages, page_size)`` replacing ``(batch, block_size)`` on
    each leaf — K (S, P, H, ps, d), V (P, H, ps, dv), plus the fp32
    scale planes on the int8 path. ``num_pages`` INCLUDES the reserved
    trash page 0 (serving/pages.py:PagePool)."""
    if cfg.block_size % page_size:
        raise ValueError(
            f"page_size ({page_size}) must divide block_size "
            f"({cfg.block_size}): the ring mask assumes whole pages"
        )
    return init_cache(cfg.replace(block_size=page_size), num_pages)


def _gather_row(leaf: jnp.ndarray, page_row: jnp.ndarray, axis: int):
    """One slot's contiguous ring view from its page-table row: gather
    the row's pages on the page axis, fold (pages, page_size) into one
    token axis, and re-add the batch-1 axis forward_chunk expects."""
    g = jnp.take(leaf, page_row, axis=axis)
    g = jnp.moveaxis(g, axis, axis + 1)  # page axis next to tokens
    shape = (
        g.shape[:axis + 1]
        + (g.shape[axis + 1] * g.shape[axis + 2],)
        + g.shape[axis + 3:]
    )
    return jnp.expand_dims(g.reshape(shape), axis)


def _scatter_row(leaf: jnp.ndarray, new_row: jnp.ndarray,
                 page_row: jnp.ndarray, axis: int):
    """Inverse of :func:`_gather_row`: split the ring view back into
    pages and scatter them to the row's physical pages. Duplicate trash
    entries in the row collide harmlessly (page 0 is write-only
    garbage); shared prefix pages receive their own unchanged values
    (the engine guarantees written positions live on private pages)."""
    r = jnp.squeeze(new_row, axis)
    pp = page_row.shape[0]
    shape = (
        r.shape[:axis + 1]
        + (pp, r.shape[axis + 1] // pp)
        + r.shape[axis + 2:]
    )
    r = jnp.moveaxis(r.reshape(shape), axis + 1, axis)
    idx = (slice(None),) * axis + (page_row,)
    return leaf.at[idx].set(r)


def gather_slot_cache(cache: list, page_row: jnp.ndarray) -> list:
    """A slot's per-layer batch-1 ring view through its page table —
    what the prefill chunk path (forward_chunk) runs against."""
    return [
        {key: _gather_row(c[key], page_row, KV_CACHE_BATCH_AXIS[key])
         for key in c}
        for c in cache
    ]


def scatter_slot_cache(cache: list, new_row: list,
                       page_row: jnp.ndarray) -> list:
    """Write an updated ring view back through the page table."""
    return [
        {key: _scatter_row(c[key], nr[key], page_row,
                           KV_CACHE_BATCH_AXIS[key])
         for key in c}
        for c, nr in zip(cache, new_row)
    ]


def copy_cache_pages(cache: list, src, dst) -> list:
    """Copy one physical page onto another across every layer/leaf —
    the device half of a copy-on-write fork (serving/pages.py): the
    shared page's prefix K/V lands in a private page the forking slot
    may write. ``src``/``dst`` are runtime int32 scalars, so forks
    never recompile."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = []
    for c in cache:
        layer = {}
        for key in c:
            axis = KV_CACHE_BATCH_AXIS[key]
            page = jnp.take(c[key], src, axis=axis)
            idx = (slice(None),) * axis + (dst,)
            layer[key] = c[key].at[idx].set(page)
        out.append(layer)
    return out


def _gather_pool_view(leaf: jnp.ndarray, page_tables: jnp.ndarray,
                      axis: int):
    """Every slot's ring view at once: (…, B, H, M, …) gathered from
    the paged leaf through the full (B, pages_per_slot) table — the
    XLA decode path's read (the Pallas kernel instead loads pages
    directly through the table, ops/decode_attention.py)."""
    B, pp = page_tables.shape
    g = jnp.take(leaf, page_tables.reshape(-1), axis=axis)
    g = g.reshape(
        leaf.shape[:axis] + (B, pp) + leaf.shape[axis + 1:]
    )
    g = jnp.moveaxis(g, axis + 1, axis + 2)  # pages next to tokens
    shape = (
        g.shape[:axis + 2]
        + (g.shape[axis + 2] * g.shape[axis + 3],)
        + g.shape[axis + 4:]
    )
    return g.reshape(shape)


def _update_pages_rows(layer_cache: dict, ks: jnp.ndarray,
                       v: jnp.ndarray, pos: jnp.ndarray,
                       write_pages: jnp.ndarray, M: int) -> dict:
    """Scatter each row's new K/V — ks (S, B, H, d), v (B, H, dv) —
    into physical page ``write_pages[b]`` at in-page offset
    ``(pos[b] % M) % page_size``. The engine redirects inactive rows
    to the trash page, which replaces the contiguous path's masked
    merge (models/decode.py:merge_cache_update)."""
    ps = layer_cache["v"].shape[-2]
    off = jax.lax.rem(
        jax.lax.rem(jnp.asarray(pos, jnp.int32), M), ps
    )
    wp = jnp.asarray(write_pages, jnp.int32)
    out = dict(layer_cache)
    if "k_scale" in layer_cache:
        kq, ksc = quantize_kv(ks)
        vq, vsc = quantize_kv(v)
        out["k"] = layer_cache["k"].at[:, wp, :, off].set(
            kq.transpose(1, 0, 2, 3)
        )
        out["k_scale"] = layer_cache["k_scale"].at[:, wp, :, off].set(
            ksc.transpose(1, 0, 2)
        )
        out["v"] = layer_cache["v"].at[wp, :, off].set(vq)
        out["v_scale"] = layer_cache["v_scale"].at[wp, :, off].set(vsc)
    else:
        dt = layer_cache["k"].dtype
        out["k"] = layer_cache["k"].at[:, wp, :, off].set(
            ks.astype(dt).transpose(1, 0, 2, 3)
        )
        out["v"] = layer_cache["v"].at[wp, :, off].set(v.astype(dt))
    return out


def _pool_attn_paged(
    x: jnp.ndarray,  # (B, E) normed single-token inputs, one per slot
    p_attn: dict,
    layer_cache: dict,  # paged leaves (page axis where the slot axis was)
    pos: jnp.ndarray,  # (B,) int32 absolute positions
    page_tables: jnp.ndarray,  # (B, pages_per_slot) int32
    write_pages: jnp.ndarray,  # (B,) int32 physical page per row's write
    layer_idx: int,
    cfg: ModelConfig,
    cos,
    sin,
):
    """The paged twin of :func:`_pool_attn`: write each row's K/V into
    its physical page (update-then-attend), then attend through the
    page table — the fused kernel loads pages directly; the XLA path
    gathers the contiguous view first."""
    B = x.shape[0]
    M = cfg.block_size
    wq, wk = _stacked_wq(p_attn)
    qs = jnp.einsum("be,sehd->sbhd", x, wq.astype(x.dtype))
    ks = jnp.einsum("be,sehd->sbhd", x, wk.astype(x.dtype))
    v = jnp.einsum("be,ehd->bhd", x, p_attn["wv"].astype(x.dtype))
    if _uses_rope(cfg):
        qs = _rope_rows(qs, cos, sin)
        ks = _rope_rows(ks, cos, sin)
    new_cache = _update_pages_rows(layer_cache, ks, v, pos, write_pages, M)
    coeffs = _layer_coeffs(cfg, p_attn, layer_idx)
    if cfg.decode_attention_impl == "pallas":
        out = decode_attention_paged(
            qs, new_cache["k"], new_cache["v"], page_tables, pos, coeffs,
            k_scale=new_cache.get("k_scale"),
            v_scale=new_cache.get("v_scale"),
        )
    else:
        view = {
            key: _gather_pool_view(new_cache[key], page_tables,
                                   KV_CACHE_BATCH_AXIS[key])
            for key in new_cache
        }
        k_eff, v_eff = _dequant_layer(view, x.dtype)
        out = decode_attention_reference(qs, k_eff, v_eff, pos, coeffs)
    out = out.reshape(B, -1)  # concat heads
    if cfg.model in ("diff", "ndiff"):
        out = common.apply_group_norm(out, p_attn["gn"], cfg)
        out = out * OUTPUT_SCALE
    return common.linear(out, p_attn["out"]), new_cache


def forward_decode_pool_paged(
    params: dict,
    tokens: jnp.ndarray,  # (B,) current token per slot row
    pos,  # (B,) int32 absolute position per row
    cache: list,  # paged cache (init_cache_paged)
    page_tables: jnp.ndarray,  # (B, pages_per_slot) int32
    write_pages: jnp.ndarray,  # (B,) int32; trash page for inactive rows
    cfg: ModelConfig,
    rope_len: int = 0,
) -> Tuple[jnp.ndarray, list]:
    """Advance the whole slot pool by one token THROUGH the page
    tables: the paged counterpart of :func:`forward_decode_pool`, same
    ring semantics and update-then-attend order, with the physical
    placement of every KV row resolved from runtime int32 tables — so
    pages can be allocated, freed, shared and forked between calls
    with ZERO recompiles (pinned by tests/test_pages.py)."""
    B = tokens.shape[0]
    M = cfg.block_size
    compute = jnp.dtype(cfg.compute_dtype)
    pos = jnp.asarray(pos, jnp.int32)
    x = params["tok_emb"][tokens].astype(compute)  # (B, E)
    cos = sin = None
    if cfg.model == "diff":
        x = x + params["pos_emb"][pos].astype(compute)
    else:
        cos_full, sin_full = rope_cos_sin(
            cfg.head_size, max(int(rope_len), M)
        )
        cos = cos_full[pos]
        sin = sin_full[pos]
    new_cache = []
    for li, blk in enumerate(params["blocks"], 1):  # 1-based schedule
        a, layer_cache = _pool_attn_paged(
            common.apply_pre_norm(x, blk["ln1"], cfg), blk["attn"],
            cache[li - 1], pos, page_tables, write_pages, li, cfg,
            cos, sin,
        )
        x = common.apply_block_ffn(x, a, blk, cfg)
        new_cache.append(layer_cache)
    x = common.apply_pre_norm(x, params["ln_f"], cfg)
    return common.linear(x, params["lm_head"]), new_cache


# ---------------------------------------------------------------------------
# Speculative multi-row decode (serving/spec.py): the verify step runs
# L = k + 1 rows per slot through the pool in ONE call — the slot's last
# emitted token plus its k draft tokens, each row at its own absolute
# position with row-causal visibility (update-then-attend: all L rows'
# K/V are written first, then each row's mask ``col <= pos[b, l]`` shows
# it exactly the rows before it). Rows past a slot's draft length (and
# every row of an inactive slot) are WRITE-REDIRECTED instead of masked:
# the contiguous pool carries one extra TRASH ROW at batch index
# ``num_slots`` (``row_target`` names each row's destination), the paged
# pool redirects to the trash page through ``write_pages`` — either way
# the jitted step needs no shape change as per-slot draft lengths vary,
# so mixed spec/non-spec traffic compiles NOTHING new.
# ---------------------------------------------------------------------------


def _update_cache_rows_spec(layer_cache: dict, ks: jnp.ndarray,
                            v: jnp.ndarray, slot: jnp.ndarray,
                            row: jnp.ndarray) -> dict:
    """Scatter N flattened verify rows' K/V — ks (S, N, H, d),
    v (N, H, dv) — into cache batch row ``row[n]`` at ring slot
    ``slot[n]``. The multi-row twin of :func:`_update_cache_rows` with
    an EXPLICIT batch-row index: valid rows name their own slot row,
    invalid rows the trash row (collisions inside the trash row are
    harmless — it is write-only garbage)."""
    out = dict(layer_cache)
    if "k_scale" in layer_cache:
        kq, ksc = quantize_kv(ks)
        vq, vsc = quantize_kv(v)
        out["k"] = layer_cache["k"].at[:, row, :, slot].set(
            kq.transpose(1, 0, 2, 3)
        )
        out["k_scale"] = layer_cache["k_scale"].at[:, row, :, slot].set(
            ksc.transpose(1, 0, 2)
        )
        out["v"] = layer_cache["v"].at[row, :, slot].set(vq)
        out["v_scale"] = layer_cache["v_scale"].at[row, :, slot].set(vsc)
    else:
        dt = layer_cache["k"].dtype
        out["k"] = layer_cache["k"].at[:, row, :, slot].set(
            ks.astype(dt).transpose(1, 0, 2, 3)
        )
        out["v"] = layer_cache["v"].at[row, :, slot].set(v.astype(dt))
    return out


def _pool_attn_spec(
    x: jnp.ndarray,  # (B, L, E) normed per-row inputs
    p_attn: dict,
    layer_cache: dict,  # contiguous (R >= B rows) OR paged leaves
    pos: jnp.ndarray,  # (B, L) int32 absolute positions
    targets: jnp.ndarray,  # (B, L) int32: cache row (contiguous) or
    #                        physical write page (paged) per verify row
    page_tables,  # (B, pages_per_slot) int32, or None on the
    #               contiguous path
    layer_idx: int,
    cfg: ModelConfig,
    cos,  # (B, L, d/2) per-row RoPE tables (None for the diff family)
    sin,
):
    """The L-row twin of :func:`_pool_attn` / :func:`_pool_attn_paged`:
    write all L rows' K/V (flattened, write-redirected), then attend
    every row with row-causal visibility through
    ops/decode_attention.py's multi-query kernel (or its XLA twin)."""
    B, L, E = x.shape
    M = cfg.block_size
    wq, wk = _stacked_wq(p_attn)
    qs = jnp.einsum("ble,sehd->sblhd", x, wq.astype(x.dtype))
    ks = jnp.einsum("ble,sehd->sblhd", x, wk.astype(x.dtype))
    v = jnp.einsum("ble,ehd->blhd", x, p_attn["wv"].astype(x.dtype))
    if _uses_rope(cfg):
        S = qs.shape[0]
        d = qs.shape[-1]
        cos_f = cos.reshape(B * L, -1)
        sin_f = sin.reshape(B * L, -1)
        qs = _rope_rows(
            qs.reshape(S, B * L, cfg.n_head, d), cos_f, sin_f
        ).reshape(qs.shape)
        ks = _rope_rows(
            ks.reshape(S, B * L, cfg.n_head, d), cos_f, sin_f
        ).reshape(ks.shape)
    S = qs.shape[0]
    ks_f = ks.reshape(S, B * L, cfg.n_head, -1)  # B, L adjacent: zero-copy
    v_f = v.reshape(B * L, cfg.n_head, -1)
    if page_tables is None:
        slot = jax.lax.rem(
            jnp.asarray(pos, jnp.int32).reshape(-1), M
        )
        new_cache = _update_cache_rows_spec(
            layer_cache, ks_f, v_f, slot, targets.reshape(-1)
        )
    else:
        new_cache = _update_pages_rows(
            layer_cache, ks_f, v_f,
            jnp.asarray(pos, jnp.int32).reshape(-1),
            targets.reshape(-1), M,
        )
    coeffs = _layer_coeffs(cfg, p_attn, layer_idx)
    if cfg.decode_attention_impl == "pallas":
        if page_tables is None:
            out = decode_attention_multi(
                qs, new_cache["k"], new_cache["v"], pos, coeffs,
                k_scale=new_cache.get("k_scale"),
                v_scale=new_cache.get("v_scale"),
            )
        else:
            out = decode_attention_multi_paged(
                qs, new_cache["k"], new_cache["v"], page_tables, pos,
                coeffs,
                k_scale=new_cache.get("k_scale"),
                v_scale=new_cache.get("v_scale"),
            )
    else:
        if page_tables is None:
            # the trash row (batch rows >= B) is never attended
            view = {
                key: (c_val[:, :B] if KV_CACHE_BATCH_AXIS[key]
                      else c_val[:B])
                for key, c_val in new_cache.items()
            }
        else:
            view = {
                key: _gather_pool_view(new_cache[key], page_tables,
                                       KV_CACHE_BATCH_AXIS[key])
                for key in new_cache
            }
        k_eff, v_eff = _dequant_layer(view, x.dtype)
        out = decode_attention_multi_reference(qs, k_eff, v_eff, pos,
                                               coeffs)
    out = out.reshape(B, L, -1)  # concat heads
    if cfg.model in ("diff", "ndiff"):
        out = common.apply_group_norm(out, p_attn["gn"], cfg)
        out = out * OUTPUT_SCALE
    return common.linear(out, p_attn["out"]), new_cache


def _spec_row_axes(cfg: ModelConfig) -> list:
    """Per-layer cache vmap axes (the engine's ``row_axes`` twin)."""
    keys = (
        ("k", "v", "k_scale", "v_scale")
        if kv_store_dtype(cfg) == "int8" else ("k", "v")
    )
    return [
        {key: KV_CACHE_BATCH_AXIS[key] for key in keys}
    ] * cfg.n_layer


def _one_row_exact(params, token, pos, cache_row, cfg: ModelConfig,
                   rope_len: int):
    """One vmap lane of the engine's XLA decode step (serving/engine.py
    ``_build_step_fns._one_row``, duplicated here so the EXACT verify
    mode is bit-identical to it by construction): re-add the batch-1
    axis forward_chunk expects, advance one token, strip it again."""
    cache_b = [
        {key: (c[key][:, None] if KV_CACHE_BATCH_AXIS[key]
               else c[key][None])
         for key in c}
        for c in cache_row
    ]
    logits, new_cache = forward_chunk(
        params, token[None, None], pos, cache_b, cfg, rope_len=rope_len
    )
    new_row = [
        {key: (c[key][:, 0] if KV_CACHE_BATCH_AXIS[key] else c[key][0])
         for key in c}
        for c in new_cache
    ]
    return logits[0, -1].astype(jnp.float32), new_row


def _exact_row_step(params, tokens_r, pos_r, valid_r, cache,
                    cfg: ModelConfig, rope_len: int):
    """One EXACT verify sub-step over the full contiguous pool: run
    the engine's own L=1 decode program (vmapped forward_chunk for the
    XLA impl, the pool-native fused path for pallas) and discard
    invalid rows' writes with the same masked merge the engine uses.
    Because every op runs at exactly the L=1 step's shapes, the
    sub-step is bit-identical to a plain engine iteration — at ANY
    model size (batched multi-row matmuls reassociate their reductions
    once the contraction is large enough; per-lane/M-preserving shapes
    cannot)."""
    if cfg.decode_attention_impl == "pallas":
        logits, new_cache = forward_decode_pool(
            params, tokens_r, pos_r, cache, cfg, rope_len=rope_len
        )
        logits = logits.astype(jnp.float32)
    else:
        axes = _spec_row_axes(cfg)
        logits, new_cache = jax.vmap(
            lambda t, p, c: _one_row_exact(params, t, p, c, cfg,
                                           rope_len),
            in_axes=(0, 0, axes), out_axes=(0, axes),
        )(tokens_r, pos_r, cache)
    return logits, merge_cache_update(valid_r, new_cache, cache)


def forward_decode_spec(
    params: dict,
    tokens: jnp.ndarray,  # (B, L) per-row tokens (row 0 = last emitted)
    pos,  # (B, L) int32 absolute position per row
    cache: list,  # contiguous cache with R >= B batch rows
    cfg: ModelConfig,
    row_target: jnp.ndarray,  # (B, L) int32 cache row per verify row
    rope_len: int = 0,
    batched: bool = False,
) -> Tuple[jnp.ndarray, list]:
    """Advance the whole slot pool by an L-row verify block: returns
    ``((B, L, V) logits, updated cache)``. Row (b, 0) reruns the slot's
    last emitted token exactly like :func:`forward_decode_pool`; rows
    1..L-1 carry its draft tokens at pos+1.. with row-causal
    visibility. ``row_target`` redirects rows past a slot's draft
    length (and inactive slots' rows) to the pool's trash row (batch
    index B), so the rejected suffix never lands in live cache state —
    the ring/page cursors "roll back" for free because visibility
    derives purely from position arithmetic.

    Two verify formulations (``ServingConfig.spec_verify``):

    - ``batched=False`` (EXACT, the serving default): a static unroll
      of L engine-native L=1 sub-steps inside one jitted program.
      Every matmul keeps the plain decode step's shapes, so greedy
      spec output is bit-identical to non-spec decoding at ANY model
      size — the property the parity pins rely on.
    - ``batched=True``: all L rows in ONE pass — one fused multi-query
      attention call per layer (ops/decode_attention.py
      ``decode_attention_multi``: every row's ring streamed once,
      row-causal masks, int8 dequant fused) and (B, L)-batched
      projections/FFN. This is the bandwidth-optimal TPU formulation
      (the KV stream and weight reads amortize over the L rows);
      large-contraction XLA matmuls may reassociate their reductions
      vs the 1-row step, so greedy ties can resolve differently at
      scale (bit-identical at the pinned test sizes; the sampled
      distribution is unchanged either way).
    """
    B, L = tokens.shape
    pos = jnp.asarray(pos, jnp.int32)
    if batched:
        return _forward_decode_spec_batched(
            params, tokens, pos, cache, cfg, row_target, rope_len
        )
    R = cache[0]["v"].shape[0]
    padn = R - B
    valid = jnp.asarray(row_target, jnp.int32) < B
    rows = []
    for l in range(L):
        t_r, p_r, v_r = tokens[:, l], pos[:, l], valid[:, l]
        if padn:
            t_r = jnp.concatenate([t_r, jnp.zeros((padn,), t_r.dtype)])
            p_r = jnp.concatenate([p_r, jnp.zeros((padn,), p_r.dtype)])
            v_r = jnp.concatenate([v_r, jnp.zeros((padn,), bool)])
        lg, cache = _exact_row_step(params, t_r, p_r, v_r, cache, cfg,
                                    rope_len)
        rows.append(lg[:B])
    return jnp.stack(rows, axis=1), cache


def _forward_decode_spec_batched(params, tokens, pos, cache,
                                 cfg: ModelConfig, row_target,
                                 rope_len: int):
    B, L = tokens.shape
    M = cfg.block_size
    compute = jnp.dtype(cfg.compute_dtype)
    x = params["tok_emb"][tokens].astype(compute)  # (B, L, E)
    cos = sin = None
    if cfg.model == "diff":
        x = x + params["pos_emb"][pos].astype(compute)
    else:
        cos_full, sin_full = rope_cos_sin(
            cfg.head_size, max(int(rope_len), M)
        )
        cos = cos_full[pos]  # (B, L, d/2)
        sin = sin_full[pos]
    new_cache = []
    for li, blk in enumerate(params["blocks"], 1):  # 1-based schedule
        a, layer_cache = _pool_attn_spec(
            common.apply_pre_norm(x, blk["ln1"], cfg), blk["attn"],
            cache[li - 1], pos, row_target, None, li, cfg, cos, sin,
        )
        x = common.apply_block_ffn(x, a, blk, cfg)
        new_cache.append(layer_cache)
    x = common.apply_pre_norm(x, params["ln_f"], cfg)
    return common.linear(x, params["lm_head"]), new_cache


def forward_decode_spec_paged(
    params: dict,
    tokens: jnp.ndarray,  # (B, L) per-row tokens
    pos,  # (B, L) int32 absolute position per row
    cache: list,  # paged cache (init_cache_paged)
    page_tables: jnp.ndarray,  # (B, pages_per_slot) int32
    write_pages: jnp.ndarray,  # (B, L) int32; trash page for invalid rows
    cfg: ModelConfig,
    rope_len: int = 0,
    batched: bool = False,
) -> Tuple[jnp.ndarray, list]:
    """Paged twin of :func:`forward_decode_spec`: every verify row's
    K/V lands in the physical page ``write_pages[b, l]`` names (the
    trash page for rows past the slot's draft length), and each row
    attends THROUGH the same runtime page tables as the L=1 step — so
    draft lengths, page churn and COW forks between calls compile
    nothing new. EXACT mode unrolls L ``forward_decode_pool_paged``
    sub-steps (bit-identical to the engine's paged L=1 step at any
    size); batched mode streams each slot's pages ONCE for all L rows
    through the scalar-prefetch multi-query kernel
    (``decode_attention_multi_paged``)."""
    B, L = tokens.shape
    pos = jnp.asarray(pos, jnp.int32)
    if not batched:
        rows = []
        for l in range(L):
            lg, cache = forward_decode_pool_paged(
                params, tokens[:, l], pos[:, l], cache, page_tables,
                write_pages[:, l], cfg, rope_len=rope_len,
            )
            rows.append(lg.astype(jnp.float32))
        return jnp.stack(rows, axis=1), cache
    M = cfg.block_size
    compute = jnp.dtype(cfg.compute_dtype)
    x = params["tok_emb"][tokens].astype(compute)  # (B, L, E)
    cos = sin = None
    if cfg.model == "diff":
        x = x + params["pos_emb"][pos].astype(compute)
    else:
        cos_full, sin_full = rope_cos_sin(
            cfg.head_size, max(int(rope_len), M)
        )
        cos = cos_full[pos]
        sin = sin_full[pos]
    new_cache = []
    for li, blk in enumerate(params["blocks"], 1):  # 1-based schedule
        a, layer_cache = _pool_attn_spec(
            common.apply_pre_norm(x, blk["ln1"], cfg), blk["attn"],
            cache[li - 1], pos, write_pages, page_tables, li, cfg,
            cos, sin,
        )
        x = common.apply_block_ffn(x, a, blk, cfg)
        new_cache.append(layer_cache)
    x = common.apply_pre_norm(x, params["ln_f"], cfg)
    return common.linear(x, params["lm_head"]), new_cache


@partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k")
)
def generate_cached(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    max_new_tokens: int,
    rng: jax.Array,
    temperature: float = 1.0,
    top_k=None,
) -> jnp.ndarray:
    """KV-cached counterpart of models/generate.py: same sampling contract
    (temperature-1 categorical over the last position, prompt included in
    the return), O(T) per new token instead of O(T^2).

    RoPE families (control/ndiff) may generate PAST block_size: the ring
    cache rolls the oldest keys off, so every step attends over exactly
    the last block_size tokens at O(T)/token instead of the windowed
    recompute's O(T^2) — sliding-window attention semantics, which
    equals the reference's crop (control.py:163-171) exactly for
    single-layer models and up to the block boundary for any depth; for
    deeper models past the boundary the crop's per-step full recompute
    is Omega(M^2)/token by construction and the cached fast path keeps
    richer (own-window) activations instead — see the module docstring.
    The diff family (learned absolute position table,
    diff_transformer.py:158) cannot roll its cache — each window slide
    re-embeds every cached position — so it keeps the
    ``T0 + max_new_tokens <= block_size`` bound and models/generate.py
    for longer runs."""
    B, T0 = idx.shape
    M = cfg.block_size
    if cfg.model == "diff" and T0 + max_new_tokens > M:
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"block_size ({M}) and the diff family's learned absolute "
            "position table cannot roll with a KV cache; use "
            "models.generate for its sliding-window behavior"
        )
    # the reference crops the prompt itself to the last block_size tokens
    # (control.py:165); rebasing the crop to position 0 is invariant for
    # RoPE (relative positions) and exact for diff (which fits by the
    # guard above)
    if T0 > M:
        idx_cond = idx[:, -M:]
        Tc = M
    else:
        idx_cond = idx
        Tc = T0
    total = Tc + max_new_tokens
    cache = init_cache(cfg, B)
    logits, cache = forward_chunk(params, idx_cond, 0, cache, cfg, rope_len=total)
    samples = jnp.zeros((B, max_new_tokens), idx.dtype)

    rng, key0 = jax.random.split(rng)
    first = sample_token(
        key0, logits[:, -1, :].astype(jnp.float32), temperature, top_k
    ).astype(idx.dtype)
    samples = samples.at[:, 0].set(first)

    def body(i, carry):
        cache, samples, rng = carry
        rng, key = jax.random.split(rng)
        prev = samples[:, i - 1]
        if cfg.decode_attention_impl == "pallas":
            # fused pool step: all B rows share the position here, but
            # the kernel path is the same one the serving engine runs
            # with per-row positions
            last, cache = forward_decode_pool(
                params, prev, jnp.full((B,), Tc + i - 1, jnp.int32),
                cache, cfg, rope_len=total,
            )
        else:
            logits, cache = forward_chunk(
                params, prev[:, None], Tc + i - 1, cache, cfg,
                rope_len=total,
            )
            last = logits[:, -1, :]
        nxt = sample_token(
            key, last.astype(jnp.float32), temperature, top_k
        ).astype(samples.dtype)
        samples = samples.at[:, i].set(nxt)
        return cache, samples, rng

    if max_new_tokens > 1:
        _, samples, _ = jax.lax.fori_loop(
            1, max_new_tokens, body, (cache, samples, rng)
        )
    return jnp.concatenate([idx, samples], axis=1)
