"""Autoregressive sampling.

Behavioral match for the reference's ``generate`` (control.py:163-171,
diff_transformer.py:177-185, Ndiff_transformer.py:232-241): crop the
context to the last ``block_size`` tokens, run a full forward, take the
last position's logits, and sample at temperature 1 with no top-k/top-p
(``torch.multinomial`` over the softmax == Gumbel sampling via
``jax.random.categorical``).

TPU re-design: instead of the reference's Python loop over a growing
tensor (O(T^2) recompile-inducing dynamic shapes), a single jitted
``lax.fori_loop`` carries a fixed ``(B, block_size)`` window buffer.
Positions stay left-aligned exactly as the reference's crop does; slots
past the current length are garbage but cannot influence earlier
positions under the causal mask.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models.registry import model_forward


def sample_token(
    key: jax.Array,
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k=None,
) -> jnp.ndarray:
    """One sampling step over (B, V) fp32 logits -> (B,) token ids.

    Defaults reproduce the reference contract exactly: temperature 1, no
    top-k (``torch.multinomial`` over softmax, control.py:168-169) — the
    division by 1.0 is exact, so default draws are bit-identical to a
    bare ``jax.random.categorical``. ``temperature <= 0`` means greedy
    argmax; ``top_k`` keeps only the k highest logits (framework
    extensions beyond the reference, off by default)."""
    if top_k is not None and int(top_k) > 0:  # <=0 means off (HF convention)
        k = min(int(top_k), logits.shape[-1])  # clamp to vocab size
        vals = jax.lax.top_k(logits, k)[0]
        logits = jnp.where(logits < vals[:, -1:], -jnp.inf, logits)
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(key, logits / temperature, axis=-1)


@partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k")
)
def generate(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    max_new_tokens: int,
    rng: jax.Array,
    temperature: float = 1.0,
    top_k=None,
) -> jnp.ndarray:
    """idx: (B, T0) prompt with 0 < T0 <= block_size. Returns
    (B, T0 + max_new_tokens), prompt included, like the reference."""
    B, T0 = idx.shape
    S = cfg.block_size
    if not 0 < T0 <= S:
        raise ValueError(f"prompt length {T0} must be in (0, block_size={S}]")

    window = jnp.zeros((B, S), idx.dtype).at[:, :T0].set(idx)
    samples = jnp.zeros((B, max_new_tokens), idx.dtype)

    def body(i, carry):
        window, length, samples, rng = carry
        rng, sample_key = jax.random.split(rng)
        logits, _ = model_forward(params, window, cfg)
        # logits at the last real position (control.py:167)
        last = logits[:, length - 1, :].astype(jnp.float32)
        nxt = sample_token(sample_key, last, temperature, top_k).astype(window.dtype)
        samples = samples.at[:, i].set(nxt)

        def append(w):
            return w.at[:, length].set(nxt)

        def shift(w):
            return jnp.concatenate([w[:, 1:], nxt[:, None]], axis=1)

        window = jax.lax.cond(length < S, append, shift, window)
        length = jnp.minimum(length + 1, S)
        return window, length, samples, rng

    _, _, samples, _ = jax.lax.fori_loop(
        0, max_new_tokens, body, (window, jnp.asarray(T0, jnp.int32), samples, rng)
    )
    return jnp.concatenate([idx, samples], axis=1)
