"""Autoregressive sampling.

Behavioral match for the reference's ``generate`` (control.py:163-171,
diff_transformer.py:177-185, Ndiff_transformer.py:232-241): crop the
context to the last ``block_size`` tokens, run a full forward, take the
last position's logits, and sample at temperature 1 with no top-k/top-p
(``torch.multinomial`` over the softmax == Gumbel sampling via
``jax.random.categorical``).

TPU re-design: instead of the reference's Python loop over a growing
tensor (O(T^2) recompile-inducing dynamic shapes), a single jitted
``lax.fori_loop`` carries a fixed ``(B, block_size)`` window buffer.
Positions stay left-aligned exactly as the reference's crop does; slots
past the current length are garbage but cannot influence earlier
positions under the causal mask.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.models.registry import model_forward


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens"))
def generate(
    params: dict,
    idx: jnp.ndarray,
    cfg: ModelConfig,
    max_new_tokens: int,
    rng: jax.Array,
) -> jnp.ndarray:
    """idx: (B, T0) prompt with 0 < T0 <= block_size. Returns
    (B, T0 + max_new_tokens), prompt included, like the reference."""
    B, T0 = idx.shape
    S = cfg.block_size
    if not 0 < T0 <= S:
        raise ValueError(f"prompt length {T0} must be in (0, block_size={S}]")

    window = jnp.zeros((B, S), idx.dtype).at[:, :T0].set(idx)
    samples = jnp.zeros((B, max_new_tokens), idx.dtype)

    def body(i, carry):
        window, length, samples, rng = carry
        rng, sample_key = jax.random.split(rng)
        logits, _ = model_forward(params, window, cfg)
        # logits at the last real position (control.py:167)
        last = logits[:, length - 1, :].astype(jnp.float32)
        nxt = jax.random.categorical(sample_key, last, axis=-1).astype(window.dtype)
        samples = samples.at[:, i].set(nxt)

        def append(w):
            return w.at[:, length].set(nxt)

        def shift(w):
            return jnp.concatenate([w[:, 1:], nxt[:, None]], axis=1)

        window = jax.lax.cond(length < S, append, shift, window)
        length = jnp.minimum(length + 1, S)
        return window, length, samples, rng

    _, _, samples, _ = jax.lax.fori_loop(
        0, max_new_tokens, body, (window, jnp.asarray(T0, jnp.int32), samples, rng)
    )
    return jnp.concatenate([idx, samples], axis=1)
