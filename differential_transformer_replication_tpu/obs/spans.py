"""Host-side span tracer emitting Chrome trace-event JSON.

Where a ``jax.profiler`` trace (utils/profiling.py) shows the DEVICE
timeline — XLA ops, fusions, HBM — this tracer shows the HOST side the
device view cannot: how long the trainer waited for data vs. dispatched
vs. blocked on results, or where one serving iteration spent its wall
time across schedule / prefill / decode / sample / emit. Both views
open in the same UI (Perfetto, https://ui.perfetto.dev, or
``chrome://tracing``).

Design points:

- **Complete events** (``"ph": "X"``): each span is one record with a
  start timestamp and duration, so nesting needs no begin/end pairing
  and a crashed process loses at most the spans still open.
- **Thread-safe**: spans record the emitting thread's id (``tid``), so
  the trainer loop, the serving engine thread, and HTTP handler threads
  each get their own track; the buffer append is lock-protected.
- **Bounded**: the in-memory buffer flushes to disk every
  ``flush_every`` events; ``close()`` finalizes a VALID JSON document
  (the JSON Array Format — a trailing ``]`` is optional for Perfetto,
  but we always write one so ``json.load`` round-trips in tests/tools).
- **Free when off**: :data:`NOOP_TRACER` is a singleton whose ``span``
  returns a shared no-op context manager — the instrumented hot loops
  pay one attribute call and no allocation when tracing is disabled.
- **Parented spans**: cross-process correlation rides the ordinary
  ``args`` dict — a span emitted with ``trace_id``/``span_id``/
  ``parent_id`` args (minted by obs/trace.py) joins the fleet-wide
  timeline ``tools/trace_stitch.py`` assembles; :meth:`complete` emits
  one over an already-measured interval (a request's submit→finish
  lifetime). NOOP-safe: the no-op tracer accepts the same calls.
- **Crash-safe tail**: every tracer registers an ``atexit`` close, so
  a process that exits without reaching its explicit closer (SIGTERM
  drain paths close eagerly) still terminates a valid JSON document.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import List, Optional


class _NoopSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer._emit_complete(
            self._name, self._t0, time.perf_counter(), self._args
        )
        return False


class SpanTracer:
    """Append-to-file Chrome tracer; see module docstring.

    ``path`` is the output ``.trace.json``. The file is (re)created at
    construction; events stream into it as the buffer fills, and
    :meth:`close` terminates the JSON array. ``process_name`` labels the
    track group in the viewer (trainer vs. serving engine).
    """

    def __init__(self, path: str, process_name: str = "host",
                 flush_every: int = 512):
        self.path = path
        self.pid = os.getpid()
        self._lock = threading.Lock()
        self._buf: List[dict] = []
        self._flush_every = max(1, flush_every)
        self._wrote_any = False
        self._closed = False
        # perf_counter has an arbitrary epoch; anchor it to wall clock
        # once so trace timestamps are meaningful across processes
        self._epoch = time.time() - time.perf_counter()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "w", encoding="utf-8")
        self._fh.write("[\n")
        self._meta("process_name", {"name": process_name})
        self._meta("process_sort_index", {"sort_index": 0})
        # safety net: a SIGTERM'd (or plainly exiting) process must not
        # lose its buffered tail — the graceful-drain paths close
        # explicitly, and close() is idempotent, so double-closing here
        # is free
        atexit.register(self.close)

    # -- recording -----------------------------------------------------

    def span(self, name: str, **args) -> _Span:
        """``with tracer.span("decode", iteration=i): ...`` — one
        complete event covering the with-block, on the calling thread's
        track."""
        return _Span(self, name, args or None)

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (``"ph": "i"``)."""
        self._append({
            "name": name, "ph": "i", "s": "t",
            "ts": self._ts(time.perf_counter()),
            "pid": self.pid, "tid": threading.get_ident() % 2**31,
            **({"args": args} if args else {}),
        })

    def counter(self, name: str, **values) -> None:
        """A counter track sample (``"ph": "C"``) — queue depth, slot
        occupancy — rendered as a stacked area chart by the viewer."""
        self._append({
            "name": name, "ph": "C",
            "ts": self._ts(time.perf_counter()),
            "pid": self.pid, "tid": 0, "args": values,
        })

    def complete(self, name: str, t0: float, t1: float, **args) -> None:
        """One complete event over an ALREADY-MEASURED
        ``perf_counter`` interval — for spans whose start was recorded
        before the emitter knew whether (or where) they would end, e.g.
        a request's submit→finish lifetime stamped with its trace
        context (``trace_id``/``span_id``/``parent_id`` ride in
        ``args`` like any other; obs/trace.py mints them)."""
        self._emit_complete(name, t0, t1, args or None)

    # -- internals -----------------------------------------------------

    def _ts(self, perf_t: float) -> float:
        return (perf_t + self._epoch) * 1e6  # microseconds

    def _meta(self, name: str, args: dict) -> None:
        self._append({
            "name": name, "ph": "M", "pid": self.pid, "tid": 0,
            "args": args,
        })

    def _emit_complete(self, name: str, t0: float, t1: float,
                       args: Optional[dict]) -> None:
        ev = {
            "name": name, "ph": "X",
            "ts": self._ts(t0), "dur": max(0.0, (t1 - t0) * 1e6),
            "pid": self.pid, "tid": threading.get_ident() % 2**31,
        }
        if args:
            ev["args"] = args
        self._append(ev)

    def _append(self, event: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._buf.append(event)
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf:
            return
        chunks = []
        for ev in self._buf:
            chunks.append(("," if self._wrote_any else "")
                          + json.dumps(ev, separators=(",", ":")) + "\n")
            self._wrote_any = True
        self._buf.clear()
        self._fh.write("".join(chunks))

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._flush_locked()
                self._fh.flush()

    def close(self) -> None:
        """Flush and terminate the JSON array; idempotent."""
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._fh.write("]\n")
            self._fh.close()
            self._closed = True


class _NoopTracer:
    """Shared do-nothing tracer so instrumentation sites never branch."""

    __slots__ = ()
    path = None

    def span(self, name: str, **args) -> _NoopSpan:
        return _NOOP_SPAN

    def instant(self, name: str, **args) -> None:
        pass

    def counter(self, name: str, **values) -> None:
        pass

    def complete(self, name: str, t0: float, t1: float, **args) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NOOP_TRACER = _NoopTracer()
