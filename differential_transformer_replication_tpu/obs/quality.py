"""Model-quality observability: fingerprints + drift scores.

Every observability layer before this one watches *time* (latency
histograms, SLO burn, device profiles). This module watches *tokens*:
the per-token quality signals the jitted decode step already computes
(sampled-distribution entropy, top-1 logit margin — models/decode.py:
quality_vector) are folded into fixed-bin quantile sketches, and a
sketch recorded from a known-good window becomes a reference
**fingerprint** that live traffic is compared against with a
PSI-style drift score (``serving_quality_drift`` on /metrics).

Why PSI (population stability index) and not a mean delta: a broken
int8 scale or a collapsed λ schedule shifts the SHAPE of the entropy/
margin distributions long before it moves their means — PSI over
fixed bins (``sum((p-q) * ln(p/q))`` with smoothing) is the standard
credit-risk/ML-monitoring statistic for exactly that, is O(bins) to
compare, and needs no raw-sample retention. Conventional reading:
< 0.1 stable, 0.1-0.25 drifting, > 0.25 shifted — the default canary
budget (AutoscalerConfig.canary_max_drift) sits at the upper knee.

Degradation contract ("no signal", never a crash): non-finite
observations are SKIPPED at ``add``, a sketch with fewer than
``MIN_DRIFT_COUNT`` live observations scores 0.0, and a missing
reference scores 0.0 — a NaN-poisoned quality tail (``quality_nan``
fault) degrades telemetry to silence while decode keeps stepping.

Stdlib only — no jax, no numpy — so the control plane
(tools/autoscaler.py, tools/slo_report.py) and tests can import it
without device initialization, same posture as obs/registry.py.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, Optional, Sequence

# Fixed bin ladders. Entropy of a categorical over V tokens lives in
# [0, ln V] — ~11 nats covers V = 60k; margins are logit differences,
# a few nats for a confident model, tens for a peaked one. Fixed (not
# data-derived) edges keep reference and live sketches comparable
# across processes and releases without negotiating bins.
ENTROPY_BINS = (0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 2.5,
                3.0, 4.0, 5.0, 6.0, 8.0, 11.0)
MARGIN_BINS = (0.05, 0.125, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 3.0,
               4.0, 6.0, 8.0, 12.0, 16.0, 24.0)

# Below this many live observations a drift score is noise, not
# signal: PSI with heavy smoothing on a handful of tokens swings past
# any sane budget. The judge treats "too thin" as 0.0 (no signal).
MIN_DRIFT_COUNT = 32

# Laplace-style smoothing mass per bin when comparing sketches: keeps
# ln(p/q) finite when a bin is empty on one side.
_PSI_EPS = 1e-4

FINGERPRINT_RECORD = "quality_fingerprint"


class QuantileSketch:
    """Fixed-bin histogram sketch of one quality signal.

    ``bins`` are upper bounds of the first ``len(bins)`` buckets; one
    overflow bucket rides at the end (counts length ``len(bins)+1``).
    Non-finite values are dropped at ``add`` — "no signal" — so a NaN
    entropy can never poison a fingerprint or a drift score.
    """

    __slots__ = ("bins", "counts", "total", "_sum")

    def __init__(self, bins: Sequence[float]):
        bins = tuple(float(b) for b in bins)
        if list(bins) != sorted(bins) or len(set(bins)) != len(bins):
            raise ValueError(f"bins must be strictly increasing: {bins}")
        self.bins = bins
        self.counts = [0] * (len(bins) + 1)
        self.total = 0
        self._sum = 0.0

    def add(self, value: float) -> bool:
        """Fold one observation in; returns False (skipped) for
        non-finite values."""
        try:
            v = float(value)
        except (TypeError, ValueError):
            return False
        if not math.isfinite(v):
            return False
        lo, hi = 0, len(self.bins)
        while lo < hi:  # first bound >= v (bisect, stdlib-only)
            mid = (lo + hi) // 2
            if self.bins[mid] < v:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1
        self.total += 1
        self._sum += v
        return True

    def mean(self) -> Optional[float]:
        return self._sum / self.total if self.total else None

    def probs(self) -> list:
        """Smoothed bucket probabilities (sum to 1, never zero)."""
        n = len(self.counts)
        denom = self.total + n * _PSI_EPS
        return [(c + _PSI_EPS) / denom for c in self.counts]

    def to_dict(self) -> dict:
        return {
            "bins": list(self.bins),
            "counts": list(self.counts),
            "sum": self._sum,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        sk = cls(d["bins"])
        counts = [int(c) for c in d.get("counts", [])]
        if len(counts) != len(sk.counts):
            raise ValueError(
                f"sketch counts length {len(counts)} does not match "
                f"{len(sk.bins)} bins"
            )
        sk.counts = counts
        sk.total = sum(counts)
        sk._sum = float(d.get("sum", 0.0))
        return sk


def psi(reference: QuantileSketch, live: QuantileSketch) -> float:
    """Population stability index between two same-bin sketches.

    0.0 = identical shapes; conventional thresholds in the module
    docstring. Raises on mismatched bin ladders (a fingerprint from a
    different release of the ladder must fail loudly, not compare
    garbage bins)."""
    if reference.bins != live.bins:
        raise ValueError(
            "sketch bin ladders differ: "
            f"{reference.bins} vs {live.bins}"
        )
    score = 0.0
    for p, q in zip(live.probs(), reference.probs()):
        score += (p - q) * math.log(p / q)
    return score


def drift_score(reference: Optional[dict], live: Dict[str, QuantileSketch],
                min_count: int = MIN_DRIFT_COUNT) -> float:
    """Max PSI across the signals both sides carry; 0.0 when there is
    no reference or the live evidence is too thin ("no signal" is not
    drift). ``reference`` is a fingerprint dict (:func:`fingerprint` /
    :func:`load_fingerprint`)."""
    if not reference:
        return 0.0
    worst = 0.0
    for name, sk in live.items():
        ref = reference.get("sketches", {}).get(name)
        if ref is None or sk.total < min_count:
            continue
        try:
            worst = max(worst, psi(QuantileSketch.from_dict(ref), sk))
        except ValueError:
            # incompatible ladder: report maximal drift rather than
            # silently passing a fingerprint that cannot be compared
            return float(math.inf)
    return worst


def fingerprint(sketches: Dict[str, QuantileSketch],
                meta: Optional[dict] = None) -> dict:
    """Serializable reference fingerprint from live sketches."""
    rec = {
        "record": FINGERPRINT_RECORD,
        "sketches": {k: sk.to_dict() for k, sk in sketches.items()},
    }
    if meta:
        rec["meta"] = dict(meta)
    return rec


def save_fingerprint(path: str, rec: dict) -> None:
    """Atomic single-JSON write (tmp + rename), so a crash mid-record
    never leaves a torn reference for the fleet to judge against."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(rec, fh)
    os.replace(tmp, path)


def load_fingerprint(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        rec = json.load(fh)
    if rec.get("record") != FINGERPRINT_RECORD:
        raise ValueError(
            f"{path} is not a quality fingerprint "
            f"(record={rec.get('record')!r})"
        )
    return rec


class QualityMonitor:
    """Live entropy/margin sketches + drift vs an optional reference.

    The engine owns one of these when quality telemetry is on: every
    emitted token's finite signals fold in via :meth:`observe`, the
    gauge-refresh path reads :meth:`drift`, and ``--quality-record``
    snapshots :meth:`fingerprint` at drain. Host-side and unlocked —
    all calls happen on the engine thread, like the StatsMap."""

    def __init__(self, reference: Optional[dict] = None):
        self.reference = reference
        self.entropy = QuantileSketch(ENTROPY_BINS)
        self.margin = QuantileSketch(MARGIN_BINS)
        self.skipped = 0  # non-finite observations ("no signal")

    def observe(self, entropy: float, margin: float) -> None:
        if not self.entropy.add(entropy):
            self.skipped += 1
        if not self.margin.add(margin):
            self.skipped += 1

    def drift(self) -> float:
        return drift_score(
            self.reference,
            {"entropy": self.entropy, "margin": self.margin},
        )

    def fingerprint(self, meta: Optional[dict] = None) -> dict:
        return fingerprint(
            {"entropy": self.entropy, "margin": self.margin}, meta=meta
        )

    def stats(self) -> dict:
        """One flat host-side view (serve_bench / engine.quality_row)."""
        return {
            "entropy_mean": self.entropy.mean(),
            "margin_mean": self.margin.mean(),
            "tokens_observed": self.entropy.total,
            "no_signal_observations": self.skipped,
            "drift": self.drift(),
        }


def quality_row(monitor: QualityMonitor, iteration: int,
                lambdas: Optional[dict] = None) -> dict:
    """One ``{"record": "quality"}`` JSONL row — the serving twin of
    the trainer's introspection records. λ keys reuse the
    ``lambda_l<k>`` / ``lambda_l<k>_t<j>`` schema (obs/introspect.py)
    so tools/lambda_report.py --serving renders fleet rows beside
    training ones, and tools/metrics_report.py summarizes/gates the
    drift column."""
    row = {"record": "quality", "iter": int(iteration)}
    for k, v in monitor.stats().items():
        row[k] = round(v, 6) if isinstance(v, float) else v
    for k, v in (lambdas or {}).items():
        row[k] = round(float(v), 6)
    return row


# import-friendly alias: serving/engine.py has a ``quality_row`` METHOD
# on the engine, so it imports the free function under this name
build_quality_row = quality_row
