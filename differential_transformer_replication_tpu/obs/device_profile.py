"""Continuous on-device profiling: a sampled capture-window scheduler.

``tools/profile_step.py`` gives the device-side breakdown once, when a
human runs it. This module makes that lens CONTINUOUS: every
``every``-th step of a production loop (trainer iteration, serving
engine iteration) is wrapped in a ``jax.profiler`` trace to a rotating
spool directory, parsed OFF-LOOP on a daemon worker thread
(obs/xprof.py — stdlib, no jax on the worker), and published three
ways:

- **registry** (obs/registry.py): ``device_step_ms_bucket{bucket=}``
  gauges (the step-time decomposition — flash_attention / fused_ffn /
  decode_attention / collectives / rest), ``device_busy_ms``,
  ``device_mfu`` (when the caller supplied a FLOPs estimate), and
  ``device_profile_captures_total`` / ``_failures_total`` /
  ``_skipped_total`` counters — scraped from ``/metrics`` like every
  other gauge;
- **metrics.jsonl**: one ``{"record": "device_profile", ...}`` row per
  capture through the caller's sink (the trainer passes
  ``MetricLogger.log_record``) or an owned JSONL file (the serving
  engine spools ``<spool>/metrics.jsonl``) — the machine-readable
  trajectory ``tools/metrics_report.py`` summarizes and
  ``tools/perf_gate.py`` gates;
- **device trace lane**: ``<spool>/device-NNNN.trace.json``, a Chrome
  trace of the captured window's device ops, anchored to the host wall
  clock and join-keyed (``capture`` arg) to the ``device_capture``
  host span this sampler emits through the caller's SpanTracer — so
  ``tools/trace_stitch.py`` merges host + device into ONE Perfetto
  timeline, HTTP request down to Pallas kernel.

Scheduling contract (the hot-loop invariants):

- **Uncaptured steps cost a host-side integer compare.**
  :meth:`maybe_begin` on a non-due step is ``step % every`` plus a
  comparison — no allocation, no lock, no syscall (measured ~0.1 µs;
  pinned loosely by test).
- **Capture wraps an ALREADY-COMPILED step.** The sampler never
  captures the FIRST step it sees (a fresh run's step 0 and a resumed
  run's restored iterate both compile) and adds no device ops, so the
  compile count stays pinned at 1 with profiling enabled (tests hold
  this under ``RecompileSentinel`` for both the trainer step and the
  engine's decode; see ANALYSIS.md).
- **Back-pressure by deferral** (the ckpt_writer model adapted for a
  sampler): at most one parse job is in flight; a capture that comes
  due while the worker is still parsing the previous one is SKIPPED
  and counted (``device_profile_skipped_total``) — the spool can never
  grow faster than the worker drains it, and the loop never blocks on
  parsing.
- **Errors surfaced, never fatal.** A failed ``start_trace`` (e.g. a
  ``ProfilerWindow`` already owns the global profiler), a missing
  xplane, or a malformed proto increments the failure counter,
  publishes an ``{"error": ...}`` row, prints once — and the loop keeps
  stepping.
- **Drained on exit.** :meth:`close` rides the caller's exit closers
  (trainer finally-block, ``ServingEngine.close``): it stops any
  still-open window, finishes the queued parse, and joins the worker.

The END of a window blocks on ``sync`` (the step's loss scalar / a
cache leaf) before ``stop_trace`` so the captured step's device work is
actually inside the window — one extra device sync every ``every``
steps, amortized exactly like the trainer's log-boundary sync.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import sys
import threading
import time
from typing import Callable, Optional

from differential_transformer_replication_tpu.obs import xprof
from differential_transformer_replication_tpu.obs.registry import Registry
from differential_transformer_replication_tpu.obs.spans import NOOP_TRACER

_BUCKET_NAMES = tuple(name for name, _ in xprof.KERNEL_BUCKETS) + ("rest",)


def _jax_start_trace(path: str) -> None:
    import jax

    jax.profiler.start_trace(path)


def _jax_stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


def _jax_block(sync) -> None:
    import jax

    jax.block_until_ready(sync)


class DeviceProfileSampler:
    """See module docstring. Constructor knobs:

    ``every``            capture cadence in steps (> 0; the first step
                         seen never captures — it compiles),
    ``spool_dir``        rotating capture spool; each window lands in
                         ``cap-NNNN/`` and its parsed lane in
                         ``device-NNNN.trace.json``; only the newest
                         ``keep`` of each survive,
    ``registry``         metrics registry to publish into (an owned one
                         is created when omitted),
    ``sink``             callable given each ``device_profile`` record
                         (the trainer's ``MetricLogger.log_record``),
    ``jsonl_path``       JSONL file to append records to; ``"auto"`` =
                         ``<spool>/metrics.jsonl``; None = sink only,
    ``tracer``           obs/spans.py SpanTracer for the
                         ``device_capture`` host span (join key of the
                         stitched device lane); NOOP-safe,
    ``flops_per_step`` / ``hbm_bytes_per_step`` / ``peak_flops``
                         estimates feeding :func:`xprof.derived_metrics`
                         (``device_mfu``); None = those fields omitted,
    ``start_fn`` / ``stop_fn`` / ``block_fn``
                         the profiler seam — default to jax.profiler
                         (imported lazily, so scheduler tests run
                         jax-free with fakes).
    """

    def __init__(
        self,
        every: int,
        spool_dir: str,
        registry: Optional[Registry] = None,
        sink: Optional[Callable[[dict], None]] = None,
        jsonl_path: Optional[str] = "auto",
        tracer=None,
        process: str = "trainer",
        keep: int = 2,
        flops_per_step: Optional[float] = None,
        hbm_bytes_per_step: Optional[float] = None,
        peak_flops: float = xprof.TPU_V5E_BF16_PEAK_FLOPS,
        start_fn: Optional[Callable[[str], None]] = None,
        stop_fn: Optional[Callable[[], None]] = None,
        block_fn: Optional[Callable[[object], None]] = None,
    ) -> None:
        if every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        self._every = int(every)
        self._spool = spool_dir
        self._sink = sink
        self.last_record = None  # newest emitted row (hang reports)
        self._tracer = tracer or NOOP_TRACER
        self._process = process
        self._keep = max(1, int(keep))
        self._flops = flops_per_step
        self._hbm = hbm_bytes_per_step
        self._peak = peak_flops
        self._start = start_fn or _jax_start_trace
        self._stop = stop_fn or _jax_stop_trace
        self._block = block_fn or _jax_block
        os.makedirs(spool_dir, exist_ok=True)
        self._jsonl = None
        if jsonl_path == "auto":
            jsonl_path = os.path.join(spool_dir, "metrics.jsonl")
        if jsonl_path:
            self._jsonl = open(jsonl_path, "a", buffering=1)
        # records are emitted from the loop thread (start failures) AND
        # the parse worker; serialize the sink/file writes
        self._emit_lock = threading.Lock()

        self.registry = registry or Registry()
        self._captures = self.registry.counter(
            "device_profile_captures_total",
            "Device profile windows captured, parsed and published.",
        )
        self._failures = self.registry.counter(
            "device_profile_failures_total",
            "Capture windows that failed (profiler busy, missing or "
            "malformed xplane); surfaced, never fatal to the loop.",
        )
        self._skipped = self.registry.counter(
            "device_profile_skipped_total",
            "Due captures skipped because the parse worker was still "
            "busy (back-pressure by deferral).",
        )
        self._mfu_gauge = self.registry.gauge(
            "device_mfu",
            "Model FLOPs utilization of the last captured step "
            "(caller's FLOPs estimate / device-busy time / peak).",
        )
        self._busy_gauge = self.registry.gauge(
            "device_busy_ms",
            "Device-busy milliseconds of the last captured step.",
        )
        self._bucket_gauge = self.registry.gauge(
            "device_step_ms_bucket",
            "Step-time decomposition of the last captured step "
            "(ms attributed to each kernel bucket; obs/xprof.py).",
            labelnames=("bucket",),
        )

        # capture-window state (loop thread only)
        self._seq = 0
        self._first_step: Optional[int] = None
        self._active = False
        self._t0 = 0.0
        self._t0_wall_us = 0.0
        self._cap_dir = ""
        self._cap_step = 0
        self._warned = False
        # one-deep parse pipeline (worker thread)
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="device-profile", daemon=True
        )
        self._thread.start()

    # -- loop-side API --------------------------------------------------

    def maybe_begin(self, step: int) -> bool:
        """Start a capture window when ``step`` is due and the worker
        is idle. The non-due path — every uncaptured step — is a couple
        of integer compares. The FIRST step this sampler ever sees is
        never captured, whatever its number: a fresh run's step 0 and a
        resumed trainer's restored iterate both trace+compile the
        jitted step, and a capture window around a compile is exactly
        the misleading profile this module exists to avoid."""
        if self._first_step is None:
            self._first_step = step
        if step % self._every != 0 or step == self._first_step:
            return False
        if self._active or self._closed:
            return False
        if not self._idle.is_set():
            # the previous window is still being parsed: defer (skip)
            # rather than queue — back-pressure, sampler-style
            self._skipped.inc()
            return False
        cap_dir = os.path.join(self._spool, f"cap-{self._seq:04d}")
        try:
            os.makedirs(cap_dir, exist_ok=True)
            self._start(cap_dir)
        except Exception as e:  # profiler busy (ProfilerWindow), IO, ...
            self._failures.inc()
            # the failure must reach the metrics stream, not just the
            # counter: a run whose EVERY capture fails to start (spool
            # unwritable, another profiler owns the global state) would
            # otherwise leave zero device_profile rows and a vacuously
            # green metrics_report --max-capture-failures gate
            self._emit({
                "record": "device_profile", "step": step,
                "process": self._process,
                "error": f"capture failed to start: {e!r}",
                "capture_failures": self.failures,
            })
            if not self._warned:
                self._warned = True
                print(f"[device_profile] capture failed to start "
                      f"(continuing, counted): {e!r}", file=sys.stderr)
            return False
        self._active = True
        self._cap_dir = cap_dir
        self._cap_step = step
        self._t0 = time.perf_counter()
        self._t0_wall_us = time.time() * 1e6
        return True

    def end(self, sync=None) -> None:
        """Close the window opened by :meth:`maybe_begin` and hand the
        trace to the worker. ``sync`` is blocked on first so the
        captured step's device work lands inside the window. The
        published record's ``step`` is the value given to
        :meth:`maybe_begin` (same as the host span's)."""
        if not self._active:
            return
        self._active = False
        try:
            if sync is not None:
                self._block(sync)
        finally:
            try:
                self._stop()
            except Exception as e:
                self._failures.inc()
                print(f"[device_profile] stop_trace failed "
                      f"(continuing, counted): {e!r}", file=sys.stderr)
                return
        t1 = time.perf_counter()
        # the host span the stitched device lane aligns under; the
        # capture seq is the join key trace_stitch matches
        self._tracer.complete(
            "device_capture", self._t0, t1,
            capture=self._seq, step=self._cap_step,
        )
        self._idle.clear()
        self._q.put((
            self._seq, self._cap_dir, self._cap_step,
            self._t0_wall_us, (t1 - self._t0) * 1e3,
        ))
        self._seq += 1

    def abort(self) -> None:
        """Stop a window a CRASHED step left open (the trace is torn —
        dropped and counted); the next due step captures normally.
        Called by crash-recovery paths (ServingEngine.reset_after_crash)
        and :meth:`close`."""
        if not self._active:
            return
        self._active = False
        self._failures.inc()
        try:
            self._stop()
        except Exception:
            pass

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Drain: abort any still-open window, finish the queued parse,
        stop the worker, close the JSONL sink. Idempotent; rides the
        caller's exit closers."""
        self.abort()
        if not self._closed:
            self._closed = True
            self._q.put(None)
        self._thread.join(timeout)
        alive = self._thread.is_alive()
        if self._jsonl is not None and not alive:
            self._jsonl.close()
            self._jsonl = None
        if alive:
            raise RuntimeError(
                f"device-profile worker did not drain within {timeout}s"
            )

    # convenience counters (tests / JSON lines)
    @property
    def captures(self) -> int:
        return int(self._captures.value)

    @property
    def failures(self) -> int:
        return int(self._failures.value)

    @property
    def skipped(self) -> int:
        return int(self._skipped.value)

    # -- worker side ----------------------------------------------------

    def _worker(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                self._parse_one(*job)
            except Exception as e:  # noqa: BLE001 — surfaced, not fatal
                self._failures.inc()
                print(f"[device_profile] parse failed "
                      f"(continuing, counted): {e!r}", file=sys.stderr)
            finally:
                self._idle.set()

    def _parse_one(self, seq: int, cap_dir: str, step: int,
                   t0_wall_us: float, window_ms: float) -> None:
        record = {
            "record": "device_profile",
            "capture": seq,
            "step": step,
            "process": self._process,
            "window_ms": round(window_ms, 3),
        }
        picked = xprof.load_trace_plane(cap_dir)
        summary = (
            picked if isinstance(picked, str)
            else xprof.summarize_plane(picked[0], picked[1], steps=1)
        )
        if isinstance(summary, str):
            self._failures.inc()
            record["error"] = summary
            record["capture_failures"] = self.failures
            self._emit(record)
            return
        plane, kind = picked
        trace_path = os.path.join(
            self._spool, f"device-{seq:04d}.trace.json"
        )
        xprof.write_chrome_trace(
            trace_path,
            xprof.plane_to_chrome_events(
                plane, pid=0, anchor_us=t0_wall_us, capture=seq
            ),
        )
        busy = summary["busy_ms_per_step"]
        derived = xprof.derived_metrics(
            busy, flops_per_step=self._flops,
            hbm_bytes_per_step=self._hbm, peak_flops=self._peak,
        )
        # publish: gauges first (scrapers), then the jsonl record
        self._busy_gauge.set(busy)
        for name in _BUCKET_NAMES:
            self._bucket_gauge.set(
                summary["bucket_ms"].get(name, 0.0), bucket=name
            )
        if "mfu" in derived:
            self._mfu_gauge.set(derived["mfu"])
        self._captures.inc()
        record.update({
            "busy_ms": round(busy, 4),
            "bucket_ms": {
                k: round(v, 4) for k, v in summary["bucket_ms"].items()
            },
            "plane": summary["plane"],
            "plane_kind": summary["plane_kind"],
            "trace_file": trace_path,
            "captures": self.captures,
            "capture_failures": self.failures,
        })
        record.update({k: round(v, 4) for k, v in derived.items()})
        self._emit(record)
        self._gc(seq)

    def _emit(self, record: dict) -> None:
        record.setdefault("ts", round(time.time(), 3))
        # last published row, kept for the watchdog's hang report
        # (train/watchdog.py): "what was the device doing the last
        # time we could see it" is the first post-mortem question.
        # Plain attribute swap — atomic under the GIL, read-only
        # consumers (the hang report) tolerate a stale value.
        self.last_record = record
        with self._emit_lock:
            if self._sink is not None:
                self._sink(record)
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(record) + "\n")

    def _gc(self, newest_seq: int) -> None:
        """Rotate the spool: keep the newest ``keep`` capture dirs and
        device-lane traces, delete the rest (single writer: this
        thread)."""
        floor = newest_seq - self._keep + 1
        for name in os.listdir(self._spool):
            n = None
            if name.startswith("cap-"):
                n = name[4:]
            elif name.startswith("device-") and name.endswith(
                ".trace.json"
            ):
                n = name[7:-len(".trace.json")]
            if n is None or not n.isdigit() or int(n) >= floor:
                continue
            path = os.path.join(self._spool, name)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            else:
                try:
                    os.unlink(path)
                except OSError:
                    pass
