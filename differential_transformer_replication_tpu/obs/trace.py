"""Cross-process trace context: mint, parse, and propagate request ids.

PR 4's span tracer (obs/spans.py) shows one PROCESS's timeline; PR 6's
router spreads one REQUEST over several processes (router pick, a
failed attempt on replica A, a retried attempt on replica B, prefill
chunks and decode iterations). Nothing correlated those events — this
module is the missing join key, Dapper-style:

- every request carries a :class:`TraceContext` — a fleet-unique
  ``trace_id`` plus the ``span_id`` of the operation that currently
  owns it;
- the context travels between processes as a ``traceparent`` string
  (the W3C Trace Context shape, ``00-<trace>-<span>-01``) in the
  request's JSON body — no new headers, no proxy cooperation needed;
- each hop derives a :meth:`child` context (same ``trace_id``, fresh
  ``span_id``) and stamps its spans/instants with ``trace_id`` /
  ``span_id`` / ``parent_id`` args, so ``tools/trace_stitch.py`` can
  merge per-process trace files into one timeline and follow one
  request across lanes.

Everything here is host-side strings — trace state never reaches a
jitted function, so tracing adds ZERO recompiles (pinned by
tests/test_trace.py). Stdlib only: the router and fleet tools import
this without jax.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Optional

_HEX = frozenset("0123456789abcdef")

# W3C trace-context field widths (hex chars)
_TRACE_LEN = 32
_SPAN_LEN = 16
_VERSION = "00"
_FLAGS = "01"  # sampled


def mint_trace_id() -> str:
    """A fleet-unique 128-bit trace id (32 lowercase hex chars)."""
    return secrets.token_hex(_TRACE_LEN // 2)


def mint_span_id() -> str:
    """A 64-bit span id (16 lowercase hex chars)."""
    return secrets.token_hex(_SPAN_LEN // 2)


def _valid_hex(s: str, n: int) -> bool:
    return len(s) == n and set(s) <= _HEX and set(s) != {"0"}


@dataclass(frozen=True)
class TraceContext:
    """One request's position in its trace: the shared ``trace_id``
    plus the ``span_id`` of the current owning operation (what child
    spans parent to)."""

    trace_id: str
    span_id: str

    def child(self) -> "TraceContext":
        """Derive the context for a sub-operation: same trace, fresh
        span id. The caller's ``span_id`` becomes the child's
        ``parent_id`` in emitted span args."""
        return TraceContext(self.trace_id, mint_span_id())

    def to_traceparent(self) -> str:
        """Serialize for the wire (the W3C ``traceparent`` shape)."""
        return f"{_VERSION}-{self.trace_id}-{self.span_id}-{_FLAGS}"


def mint() -> TraceContext:
    """A brand-new root context (the router — or a replica hit
    directly — mints one for requests that arrive without)."""
    return TraceContext(mint_trace_id(), mint_span_id())


def parse_traceparent(value) -> Optional[TraceContext]:
    """Parse a ``traceparent`` string into a :class:`TraceContext`;
    returns None for anything malformed (an unparseable header must
    degrade into a fresh trace, never a failed request)."""
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if version != _VERSION and not (
        len(version) == 2 and set(version) <= _HEX
    ):
        return None
    if not _valid_hex(trace_id, _TRACE_LEN):
        return None
    if not _valid_hex(span_id, _SPAN_LEN):
        return None
    return TraceContext(trace_id, span_id)


def child_span_args(ctx: TraceContext) -> dict:
    """Args for a NEW span emitted under ``ctx``: fresh ``span_id``,
    parented to the context's current span."""
    child = ctx.child()
    return {"trace_id": ctx.trace_id, "span_id": child.span_id,
            "parent_id": ctx.span_id}


def instant_args(ctx: TraceContext) -> dict:
    """Args for a zero-duration marker under ``ctx`` (markers need no
    span id of their own — they hang off the owning span)."""
    return {"trace_id": ctx.trace_id, "parent_id": ctx.span_id}


def from_payload(payload: dict,
                 mint_if_absent: bool = True) -> Optional[TraceContext]:
    """Extract (or mint) the trace context of one JSON request body.
    The ``traceparent`` field is the wire contract shared by the
    router, the replica server, and any client that wants to follow
    its own request."""
    ctx = parse_traceparent(payload.get("traceparent"))
    if ctx is None and mint_if_absent:
        ctx = mint()
    return ctx
