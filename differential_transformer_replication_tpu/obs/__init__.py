"""Unified telemetry layer shared by training and serving.

Three host-side pieces, each dependency-free (stdlib only):

- :mod:`obs.registry` — a thread-safe metrics registry
  (Counter/Gauge/Histogram with label support) plus a Prometheus
  text-exposition writer. The serving server exposes it at
  ``GET /metrics``; the trainer can serve it from a sidecar port
  (``--metrics-port``).
- :mod:`obs.spans` — a span tracer emitting Chrome-trace-event JSON
  (open in Perfetto / ``chrome://tracing``) for the HOST side of a step:
  data-wait vs. dispatch vs. blocking in the trainer, schedule/prefill/
  decode/sample/emit in the serving engine. Complements the DEVICE-side
  ``utils/profiling.py`` windows (XLA op timeline).
- :mod:`obs.http` — a minimal stdlib HTTP exporter serving a registry's
  exposition (the training sidecar; the serving server wires the same
  rendering into its own handler).

Cross-process additions (ISSUE 7):

- :mod:`obs.trace` — request-scoped trace contexts (``trace_id`` /
  ``span_id``) minted at the router (or any entry point), carried as
  a ``traceparent`` JSON field through every hop, and stamped onto
  spans so ``tools/trace_stitch.py`` can follow one request across
  router and replica trace files.
- :mod:`obs.events` — a structured JSONL event log (request
  admitted / finished / failed / retried, replica ejection /
  re-admission, fleet launches) unifying what router, fleet
  supervisor, and server used to print ad hoc; request events carry
  ``trace_id``.
- :mod:`obs.slo` — availability and latency objectives evaluated
  against the registry's own histograms/counters, re-exposed as
  ``slo_*`` burn-rate gauges and CI-gated by ``tools/slo_report.py``.

Device-side additions (ISSUE 12):

- :mod:`obs.xprof` — stdlib xplane-protobuf parsing (the
  ``jax.profiler`` capture format): per-kernel bucket attribution
  (flash/fused-FFN/decode-attention/collectives/rest), step-time
  decomposition, derived MFU/HBM estimates, and Chrome-trace
  conversion for the stitched device lane.
- :mod:`obs.device_profile` — the sampled capture-window scheduler:
  every N steps/iterations one step is wrapped in a profiler capture,
  parsed off-loop on a daemon worker, and published as ``device_*``
  registry gauges, ``{"record": "device_profile"}`` JSONL rows, and a
  device-lane trace ``tools/trace_stitch.py`` merges under the host
  timeline. Gated in CI by ``tools/perf_gate.py``.

:mod:`obs.introspect` adds the paper-level window: a jitted-cheap
summary op extracting per-layer effective lambda (the Differential
Transformer's central learnable quantity) and per-layer-group param
norms from a train state, logged into ``metrics.jsonl`` every eval
interval (``tools/lambda_report.py`` renders the paper's
lambda-evolution figure from any run's log).
"""

from differential_transformer_replication_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS_S,
    Registry,
    parse_exposition,
    set_build_info,
)
from differential_transformer_replication_tpu.obs.spans import (
    NOOP_TRACER,
    SpanTracer,
)
from differential_transformer_replication_tpu.obs.events import (
    EventLog,
    NOOP_EVENTS,
    open_event_log,
)
from differential_transformer_replication_tpu.obs.trace import (
    TraceContext,
    parse_traceparent,
)
from differential_transformer_replication_tpu.obs.slo import (
    AvailabilityObjective,
    LatencyObjective,
    SLOMonitor,
)
from differential_transformer_replication_tpu.obs.http import (
    start_metrics_server,
)
from differential_transformer_replication_tpu.obs.device_profile import (
    DeviceProfileSampler,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "Registry",
    "parse_exposition",
    "set_build_info",
    "SpanTracer",
    "NOOP_TRACER",
    "EventLog",
    "NOOP_EVENTS",
    "open_event_log",
    "TraceContext",
    "parse_traceparent",
    "AvailabilityObjective",
    "LatencyObjective",
    "SLOMonitor",
    "start_metrics_server",
    "DeviceProfileSampler",
]
