"""Unified telemetry layer shared by training and serving.

Three host-side pieces, each dependency-free (stdlib only):

- :mod:`obs.registry` — a thread-safe metrics registry
  (Counter/Gauge/Histogram with label support) plus a Prometheus
  text-exposition writer. The serving server exposes it at
  ``GET /metrics``; the trainer can serve it from a sidecar port
  (``--metrics-port``).
- :mod:`obs.spans` — a span tracer emitting Chrome-trace-event JSON
  (open in Perfetto / ``chrome://tracing``) for the HOST side of a step:
  data-wait vs. dispatch vs. blocking in the trainer, schedule/prefill/
  decode/sample/emit in the serving engine. Complements the DEVICE-side
  ``utils/profiling.py`` windows (XLA op timeline).
- :mod:`obs.http` — a minimal stdlib HTTP exporter serving a registry's
  exposition (the training sidecar; the serving server wires the same
  rendering into its own handler).

:mod:`obs.introspect` adds the paper-level window: a jitted-cheap
summary op extracting per-layer effective lambda (the Differential
Transformer's central learnable quantity) and per-layer-group param
norms from a train state, logged into ``metrics.jsonl`` every eval
interval (``tools/lambda_report.py`` renders the paper's
lambda-evolution figure from any run's log).
"""

from differential_transformer_replication_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS_S,
    Registry,
)
from differential_transformer_replication_tpu.obs.spans import (
    NOOP_TRACER,
    SpanTracer,
)
from differential_transformer_replication_tpu.obs.http import (
    start_metrics_server,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS_S",
    "Registry",
    "SpanTracer",
    "NOOP_TRACER",
    "start_metrics_server",
]
