"""Xplane-proto parsing: the device-side profile as a library.

``jax.profiler.trace`` writes its capture as an **xplane** protobuf
(``plugins/profile/<run>/<host>.xplane.pb``) — the XLA op timeline,
per-kernel durations, HBM events. ``tools/profile_step.py`` used to
parse it inline with tensorflow's bundled proto; this module is that
logic extracted so it can run CONTINUOUSLY (obs/device_profile.py
samples production loops) and in tier-1 (a committed synthetic fixture,
tests/test_device_profile.py) — which forces two properties:

- **stdlib only.** The wire format is decoded by a ~60-line protobuf
  reader (:func:`parse_xspace`) covering exactly the fields the
  summaries read (field numbers pinned against tensorflow's
  ``xplane.proto``; cross-checked by test when tf is importable). No
  tensorflow import, no jax import — the parse can run on the
  device_profile worker thread of a jax process or in a bare CI job.
- **graceful degradation.** Every entry point that can fail on absent
  data (no trace written, no recognizable plane) returns an error
  STRING instead of raising, and callers surface it as ``{"error":
  ...}`` — a missing TPU must never crash the loop being profiled.

Plane selection: real telemetry comes from a ``/device:TPU`` plane's
"XLA Ops" line (one flat, non-overlapping event per executed op). GPU
planes are handled the same way. On CPU there is no device plane at
all — ``pick_plane`` falls back to the ``/host:CPU`` plane and
summarizes its busiest thread line; those numbers are plumbing-grade
(events nest, so sums overcount) but keep the capture->parse->publish
pipeline testable without hardware.

Bucket attribution: XLA names Pallas programs after the kernel
function, so substring membership against :data:`KERNEL_BUCKETS` is
stable across jax versions. Order matters — the decode and fused-FFN
kernels end in the flash needle ``_fwd_kernel`` and must match FIRST,
and collectives are matched on their HLO op names.
"""

from __future__ import annotations

import glob
import json
import os
import re
from collections import defaultdict
from typing import Dict, List, Optional, Tuple, Union

# Custom-kernel buckets for the grouped breakdown (see module
# docstring on matching order). "collectives" covers the HLO
# communication ops (DP all-reduce, tensor-parallel all-gather, ring
# ppermute) so a sharded step's exposed-communication share is its own
# line in the decomposition; everything unmatched is "rest".
KERNEL_BUCKETS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("decode_attention", ("_dattn_",)),
    ("fused_ffn", ("_ffn_fwd", "_ffn_bwd", "_addnorm_",
                   "fused_ffn", "fused_norm", "fused_add_norm",
                   "_swiglu2", "_norm2", "_add_norm2")),
    ("flash_attention", ("_fwd_kernel", "_bwd_dq", "_bwd_dkv", "flash",
                         "_tm_", "tm_packed")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute",
                     "collective-broadcast")),
)

# TPU v5e bf16 peak, the MFU denominator bench.py uses; callers on
# other hardware pass their own peak to derived_metrics.
TPU_V5E_BF16_PEAK_FLOPS = 197e12


# -- minimal protobuf wire reader -----------------------------------------
#
# Field numbers from tensorflow.tsl.profiler.protobuf.xplane:
#   XSpace:  planes=1 (msg)
#   XPlane:  name=2 (str), lines=3 (msg), event_metadata=4 (map entry:
#            key=1 varint, value=2 XEventMetadata{id=1, name=2})
#   XLine:   name=2 (str), timestamp_ns=3 (varint), events=4 (msg)
#   XEvent:  metadata_id=1, offset_ps=2, duration_ps=3 (varints)
# Everything else is skipped by wire type.


def _varint(buf, i: int) -> Tuple[int, int]:
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7
        if shift > 70:
            raise ValueError("varint longer than 10 bytes")


def _fields(buf):
    """Yield ``(field_number, wire_type, value)`` triples; value is an
    int for varints and a memoryview for length-delimited fields."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        wt = tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        if i > n:
            raise ValueError("truncated protobuf field")
        yield tag >> 3, wt, v


class XEvent:
    __slots__ = ("metadata_id", "offset_ps", "duration_ps")

    def __init__(self) -> None:
        self.metadata_id = 0
        self.offset_ps = 0
        self.duration_ps = 0


class XLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self) -> None:
        self.name = ""
        self.timestamp_ns = 0
        self.events: List[XEvent] = []


class XPlane:
    __slots__ = ("name", "lines", "event_names")

    def __init__(self) -> None:
        self.name = ""
        self.lines: List[XLine] = []
        self.event_names: Dict[int, str] = {}  # metadata_id -> op name

    def event_name(self, metadata_id: int) -> str:
        return self.event_names.get(metadata_id, f"<meta:{metadata_id}>")


def _parse_event(buf) -> XEvent:
    ev = XEvent()
    for fno, wt, v in _fields(buf):
        if wt != 0:
            continue
        if fno == 1:
            ev.metadata_id = v
        elif fno == 2:
            ev.offset_ps = v
        elif fno == 3:
            ev.duration_ps = v
    return ev


def _parse_line(buf) -> XLine:
    line = XLine()
    for fno, wt, v in _fields(buf):
        if fno == 2 and wt == 2:
            line.name = bytes(v).decode("utf-8", "replace")
        elif fno == 3 and wt == 0:
            line.timestamp_ns = v
        elif fno == 4 and wt == 2:
            line.events.append(_parse_event(v))
    return line


def _parse_event_metadata_entry(buf) -> Tuple[int, str]:
    """One ``event_metadata`` map entry -> (id, name)."""
    key, name = 0, ""
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            key = v
        elif fno == 2 and wt == 2:
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
    return key, name


def _parse_plane(buf) -> XPlane:
    plane = XPlane()
    for fno, wt, v in _fields(buf):
        if fno == 2 and wt == 2:
            plane.name = bytes(v).decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            plane.lines.append(_parse_line(v))
        elif fno == 4 and wt == 2:
            key, name = _parse_event_metadata_entry(v)
            plane.event_names[key] = name
    return plane


def parse_xspace(data: bytes) -> List[XPlane]:
    """Decode an ``XSpace`` protobuf into its planes. Raises
    ``ValueError`` on malformed bytes (callers that must not raise go
    through :func:`summarize_trace`, which degrades to an error
    string)."""
    planes = []
    for fno, wt, v in _fields(memoryview(data)):
        if fno == 1 and wt == 2:
            planes.append(_parse_plane(v))
    return planes


# -- plane selection + summaries ------------------------------------------


def find_xplane_pb(trace_dir: str) -> Optional[str]:
    """Newest ``*.xplane.pb`` under a ``jax.profiler.trace`` output
    directory (the profiler nests it plugins/profile/<run>/)."""
    paths = glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*", "*.xplane.pb")
    )
    return sorted(paths)[-1] if paths else None


def pick_plane(
    planes: List[XPlane], host_fallback: bool = True
) -> Union[Tuple[XPlane, str], str]:
    """The most device-like plane: TPU, then GPU, then any
    ``/device:``, then — with ``host_fallback`` — the host-CPU plane
    (CI without an accelerator; see module docstring on the caveats).
    Returns ``(plane, kind)`` or an error string."""
    for prefix, kind in (("/device:TPU", "tpu"), ("/device:GPU", "gpu"),
                         ("/device:", "device")):
        for p in planes:
            if p.name.startswith(prefix) and p.lines:
                return p, kind
    if host_fallback:
        for p in planes:
            if p.name.startswith("/host:") and p.lines:
                return p, "host"
    names = [p.name for p in planes]
    return (
        f"no device plane in the trace (planes: {names})"
        if host_fallback else
        f"no TPU plane in the trace (planes: {names})"
    )


def _main_line(plane: XPlane, kind: str) -> Union[XLine, str]:
    """The line the summary reads. Device planes: the largest "XLA Ops"
    line (flat, one event per executed op). Host fallback: the busiest
    thread line by summed duration — events NEST there (a python call
    stack), so sums overcount; plumbing-grade only."""
    if kind in ("tpu", "gpu", "device"):
        line = max(
            (l for l in plane.lines if l.name == "XLA Ops"),
            key=lambda l: len(l.events),
            default=None,
        )
        if line is None:
            return f"no 'XLA Ops' line in the {plane.name} plane"
        return line
    line = max(
        plane.lines,
        key=lambda l: sum(e.duration_ps for e in l.events),
        default=None,
    )
    if line is None or not line.events:
        return f"no events in the {plane.name} plane"
    return line


def bucket_for(name: str) -> Optional[str]:
    """First :data:`KERNEL_BUCKETS` bucket whose needles match, else
    None (-> "rest" in the decomposition)."""
    for bucket, needles in KERNEL_BUCKETS:
        if any(n in name for n in needles):
            return bucket
    return None


def load_trace_plane(
    trace_dir: str, host_fallback: bool = True
) -> Union[str, Tuple[XPlane, str]]:
    """Parse a profiler trace directory and pick its device plane;
    ``(plane, kind)`` or an error string (never raises on bad input)."""
    path = find_xplane_pb(trace_dir)
    if path is None:
        return f"no xplane.pb under {trace_dir}"
    try:
        with open(path, "rb") as f:
            planes = parse_xspace(f.read())
    except (OSError, ValueError) as e:
        return f"cannot parse {path}: {e}"
    return pick_plane(planes, host_fallback=host_fallback)


def summarize_plane(
    plane: XPlane, kind: str, steps: int = 1
) -> Union[str, dict]:
    """The per-step breakdown of one plane's main line — or an error
    string when the plane has no summarizable line.

    Keys (all ms figures divided by ``steps``):
      ``groups``          op-family name -> ms/step (the ``%family``
                          prefix of each XLA op name),
      ``kernel_buckets``  :data:`KERNEL_BUCKETS` name -> ms/step,
      ``bucket_ms``       kernel_buckets plus ``rest`` — the full
                          step-time decomposition (sums to busy),
      ``totals``/``counts``  per-op-name total ms / event counts,
      ``busy_ms_per_step``   summed event time,
      ``plane``/``plane_kind``  which plane was summarized.
    """
    line = _main_line(plane, kind)
    if isinstance(line, str):
        return line

    steps = max(1, int(steps))
    totals: dict = defaultdict(float)
    counts: dict = defaultdict(int)
    groups: dict = defaultdict(float)
    buckets: dict = defaultdict(float)
    for ev in line.events:
        name = plane.event_name(ev.metadata_id)
        ms = ev.duration_ps / 1e9
        totals[name] += ms
        counts[name] += 1
        m = re.match(r"%([a-zA-Z_\.]+)", name)
        groups[m.group(1) if m else name[:24]] += ms
        b = bucket_for(name)
        if b is not None:
            buckets[b] += ms
    busy = sum(totals.values())
    decomp = {k: v / steps for k, v in buckets.items()}
    decomp["rest"] = max(0.0, busy - sum(buckets.values())) / steps
    return {
        "groups": {k: v / steps for k, v in groups.items()},
        "kernel_buckets": {k: v / steps for k, v in buckets.items()},
        "bucket_ms": decomp,
        "totals": dict(totals),
        "counts": dict(counts),
        "busy_ms_per_step": busy / steps,
        "plane": plane.name,
        "plane_kind": kind,
    }


def summarize_trace(
    trace_dir: str, steps: int = 1, host_fallback: bool = True
) -> Union[str, dict]:
    """:func:`load_trace_plane` + :func:`summarize_plane` in one call —
    what tools/profile_step.py reports from; error-string degradation
    on any missing/malformed input."""
    picked = load_trace_plane(trace_dir, host_fallback=host_fallback)
    if isinstance(picked, str):
        return picked
    return summarize_plane(picked[0], picked[1], steps=steps)


def derived_metrics(
    busy_ms_per_step: float,
    flops_per_step: Optional[float] = None,
    hbm_bytes_per_step: Optional[float] = None,
    peak_flops: float = TPU_V5E_BF16_PEAK_FLOPS,
) -> dict:
    """MFU / HBM-bandwidth estimates from the device-busy time.

    ``mfu`` divides the caller's model-FLOPs estimate (bench.py's
    6*N*D convention for training) by busy time and hardware peak —
    the same accounting as the bench JSON's ``mfu_6nd``, so continuous
    samples and bench rounds are directly comparable.
    ``hbm_gbps`` is the achieved bandwidth implied by the caller's
    bytes-moved estimate — roofline-order, not a measurement (real HBM
    counters need the memory-profiler plugin, not the op timeline).
    """
    out: dict = {}
    busy_s = busy_ms_per_step / 1e3
    if busy_s <= 0:
        return out
    if flops_per_step:
        out["mfu"] = flops_per_step / busy_s / peak_flops
    if hbm_bytes_per_step:
        out["hbm_gbps"] = hbm_bytes_per_step / busy_s / 1e9
    return out


def embedding_param_count(
    model: str, vocab_size: int, n_embd: int, block_size: int
) -> int:
    """Parameters EXCLUDED from the 6*N*D numerator: the token
    embedding (weight-tied with the lm head, counted once) plus — for
    the diff family only — its learned absolute position table
    (control/ndiff use RoPE, no positional params). One definition,
    shared by bench.py's ``mfu_6nd`` and the trainer's continuous
    ``device_mfu``, so the two can never subtract different N."""
    n = vocab_size * n_embd
    if model == "diff":
        n += block_size * n_embd
    return n


def train_flops_per_step(
    n_params: int, n_embed_params: int, tokens_per_step: int
) -> float:
    """The 6*N*D training-FLOPs estimate over non-embedding params —
    the numerator bench.py's ``mfu_6nd`` uses, shared here so the
    continuous ``device_mfu`` gauge agrees with bench rounds."""
    return 6.0 * max(0, n_params - n_embed_params) * tokens_per_step


def train_hbm_bytes_per_step(
    n_params: int, compute_bytes: int = 2, opt_state_bytes: int = 12
) -> float:
    """Rough HBM traffic of one optimizer step: params read twice in
    compute dtype (forward + backward) plus the fp32 optimizer update
    (grad read, m/v read+write, param read+write ~ 12 bytes/param for
    AdamW with fp32 master params). Activations are excluded — with
    flash + fused FFN they are the minority term at recipe scale
    (BASELINE.md round-5/6 decompositions)."""
    return float(n_params) * (2 * compute_bytes + opt_state_bytes)


# -- device lane (Chrome trace) -------------------------------------------


def plane_to_chrome_events(
    plane: XPlane,
    pid: int = 0,
    anchor_us: Optional[float] = None,
    capture: Optional[int] = None,
    max_events: int = 50_000,
) -> List[dict]:
    """Convert one xplane into Chrome-trace complete events — the
    DEVICE lane ``tools/trace_stitch.py`` merges under the host
    timeline.

    Device timestamps have an arbitrary epoch; ``anchor_us`` (a
    wall-clock microsecond timestamp, the same epoch obs/spans.py
    anchors host spans to) shifts the earliest event there, so the lane
    lands inside the host span that wrapped the captured step even
    before trace_stitch's capture-window alignment refines it. When
    ``capture`` is given, one enclosing ``capture_window`` event
    carries it as an arg — the join key the stitcher matches against
    the host ``device_capture`` span with the same ``capture`` arg.
    """
    raw: List[Tuple[float, float, int, str]] = []  # (ts_us, dur_us, tid, name)
    for tid, line in enumerate(plane.lines):
        base_us = line.timestamp_ns / 1e3
        for ev in line.events:
            raw.append((
                base_us + ev.offset_ps / 1e6,
                ev.duration_ps / 1e6,
                tid,
                plane.event_name(ev.metadata_id),
            ))
    if not raw:
        return []
    raw.sort(key=lambda r: r[0])
    if len(raw) > max_events:
        raw = raw[:max_events]
    shift = (anchor_us - raw[0][0]) if anchor_us is not None else 0.0
    events: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": f"device [{plane.name}]"}},
    ]
    for tid, line in enumerate(plane.lines):
        if line.events:
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": line.name or f"line-{tid}"},
            })
    lo = raw[0][0] + shift
    hi = max(ts + dur for ts, dur, _, _ in raw) + shift
    if capture is not None:
        events.append({
            "name": "capture_window", "ph": "X", "pid": pid, "tid": 0,
            "ts": lo, "dur": max(0.0, hi - lo),
            "args": {"capture": int(capture)},
        })
    for ts, dur, tid, name in raw:
        events.append({
            "name": name, "ph": "X", "pid": pid, "tid": tid,
            "ts": ts + shift, "dur": dur,
        })
    return events


def write_chrome_trace(path: str, events: List[dict]) -> None:
    """One valid Chrome-trace JSON array (what Perfetto and
    tools/trace_stitch.py load)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(events, f, separators=(",", ":"))
