"""Paper-level introspection: per-layer lambda + per-group norms.

The Differential Transformer's central learnable quantity is the
per-layer lambda that weights the subtracted attention map (Ye et al.,
2024); the paper's lambda-evolution figure shows it drifting away from
the ``0.8 - 0.6*exp(-0.3*(l-1))`` init schedule during training. The
reference repo never logs it — this module closes that gap with a
jitted-cheap summary op the trainer calls every eval interval, so the
figure can be reproduced from any run's ``metrics.jsonl``
(``tools/lambda_report.py`` renders it).

``make_param_summary(cfg)`` returns a jitted ``params -> small pytree``
op touching only the lambda vectors (a few KB) and one reduction per
layer group for the norms — microseconds of device work, one compile
per param layout (it never retraces across steps: params keep their
shapes for the whole run).

Family shapes (the acceptance contract):
  - control: no lambdas — ``lambdas`` is None, only norms are logged,
  - diff:    ``lambdas`` is (n_layer,) — one effective lambda/layer,
  - ndiff:   ``lambdas`` is (n_layer, n_terms) — one per term per layer.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from differential_transformer_replication_tpu.config import ModelConfig
from differential_transformer_replication_tpu.ops.lambdas import (
    effective_diff_lambda,
    effective_ndiff_lambdas,
    lambda_init_schedule,
)


def _layer_lambdas(params: dict, cfg: ModelConfig) -> Optional[jnp.ndarray]:
    if cfg.model == "control":
        return None
    blocks = params["blocks"]
    if cfg.model == "diff":
        return jnp.stack([
            effective_diff_lambda(blk["attn"], li)
            for li, blk in enumerate(blocks, 1)  # 1-based (ops/lambdas.py)
        ])
    return jnp.stack([
        effective_ndiff_lambdas(blk["attn"], li)
        for li, blk in enumerate(blocks, 1)
    ])


def serving_lambda_summary(params: dict, cfg: ModelConfig) -> dict:
    """Host-side per-layer effective-lambda view for the SERVING
    telemetry path (serving/engine.py mirrors it into
    ``serving_lambda_mean{layer=}`` and ``{"record": "quality"}``
    rows): the same ``lambda_l<k>`` / ``lambda_l<k>_t<j>`` key schema
    as :func:`lambda_record`, so ``tools/lambda_report.py --serving``
    renders live-fleet rows beside training ones. ``lambda_l<k>`` is
    the term mean for ndiff (the gauge's value); per-term detail rides
    the ``_t<j>`` keys. Empty dict for the control family.

    Unjitted on purpose — it runs once at engine build and after a
    params rebind (the ``quality_drift`` fault), never per step."""
    import numpy as np

    lams = _layer_lambdas(params, cfg)
    if lams is None:
        return {}
    lams = np.asarray(lams)
    out = {}
    for li in range(lams.shape[0]):
        if lams.ndim == 1:  # diff: one effective lambda per layer
            out[f"lambda_l{li + 1}"] = float(lams[li])
        else:  # ndiff: per-term lambdas + their mean
            out[f"lambda_l{li + 1}"] = float(lams[li].mean())
            for tj in range(lams.shape[1]):
                out[f"lambda_l{li + 1}_t{tj}"] = float(lams[li, tj])
    return out


def group_norms(params: dict) -> dict:
    """Global L2 norm per layer group: embeddings, each block, the final
    norm + lm head — the standard per-depth training-health view."""
    embed = {
        k: v for k, v in params.items()
        if k in ("tok_emb", "pos_emb")
    }
    head = {k: v for k, v in params.items() if k in ("ln_f", "lm_head")}
    return {
        "embed": optax.global_norm(embed),
        "blocks": jnp.stack(
            [optax.global_norm(blk) for blk in params["blocks"]]
        ),
        "head": optax.global_norm(head),
    }


def make_param_summary(cfg: ModelConfig):
    """Jitted ``summary(params) -> dict`` with ``lambdas`` (see module
    docstring; absent for control) and ``param_norms`` (embed / (L,)
    blocks / head). Call on the live train state's params — sharded
    arrays are fine, the op compiles against their shardings."""

    @jax.jit
    def summary(params: dict) -> dict:
        out = {"param_norms": group_norms(params)}
        lams = _layer_lambdas(params, cfg)
        if lams is not None:
            out["lambdas"] = lams
        return out

    return summary


def lambda_record(summary_out: dict, cfg: ModelConfig,
                  grad_norms=None) -> dict:
    """Convert a fetched (host-side) summary into flat JSON-friendly
    fields for one ``metrics.jsonl`` record. Keys:

      - diff:  ``lambda_l<k>`` (1-based layer) -> float,
      - ndiff: ``lambda_l<k>_t<j>`` (0-based term, matching the
        reference's term indexing) -> float,
      - both + control: ``param_norm_embed`` / ``param_norm_l<k>`` /
        ``param_norm_head``; ``lambda_init_l<k>`` (the schedule, so the
        drift is readable without recomputing it),
      - optional ``grad_norm_*`` mirrors from the train step's
        per-group gradient norms.
    """
    import numpy as np

    rec = {}
    lams = summary_out.get("lambdas")
    if lams is not None:
        lams = np.asarray(lams)
        for li in range(lams.shape[0]):
            rec[f"lambda_init_l{li + 1}"] = round(
                float(lambda_init_schedule(li + 1)), 6
            )
            if lams.ndim == 1:  # diff: one per layer
                rec[f"lambda_l{li + 1}"] = round(float(lams[li]), 6)
            else:  # ndiff: one per term per layer
                for tj in range(lams.shape[1]):
                    rec[f"lambda_l{li + 1}_t{tj}"] = round(
                        float(lams[li, tj]), 6
                    )
    norms = summary_out["param_norms"]
    rec["param_norm_embed"] = round(float(norms["embed"]), 4)
    for li, v in enumerate(np.asarray(norms["blocks"]), 1):
        rec[f"param_norm_l{li}"] = round(float(v), 4)
    rec["param_norm_head"] = round(float(norms["head"]), 4)
    if grad_norms is not None:
        g = np.asarray(grad_norms)
        rec["grad_norm_embed"] = round(float(g[0]), 6)
        for li in range(1, g.shape[0] - 1):
            rec[f"grad_norm_l{li}"] = round(float(g[li]), 6)
        rec["grad_norm_head"] = round(float(g[-1]), 6)
    return rec
