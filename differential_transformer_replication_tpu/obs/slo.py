"""SLO objectives and burn rates computed from registry histograms.

The metrics layer (obs/registry.py) answers "what happened"; this
module answers the operator question a fleet is actually run by: "are
we meeting our objectives, and how fast are we burning the error
budget?" — the standard SRE framing:

- a **latency objective** says "``target`` of requests complete under
  ``threshold_s``" (e.g. 99% of TTFTs under 500 ms). The error ratio
  is the fraction of observations ABOVE the threshold, read from the
  cumulative histogram the engine already populates;
- an **availability objective** says "``target`` of requests succeed",
  with good/bad drawn from outcome counters;
- the **burn rate** is ``error_ratio / (1 - target)``: 1.0 means the
  budget is being spent exactly as provisioned; >1 means the service
  will blow its objective (Google SRE workbook's multi-window alerts
  gate on exactly this number).

:class:`SLOMonitor` evaluates objectives against a live registry and
re-exposes the results AS gauges (``slo_burn_rate`` /
``slo_error_ratio`` / ``slo_target``) in the same registry, so every
scrape of ``/metrics`` (or the router's ``/fleet/metrics``) carries
the judgment alongside the raw data, and ``tools/slo_report.py
--check`` can gate CI on it. Counters and histograms are cumulative,
so the monitor reports both the lifetime burn and the burn over the
window since its previous evaluation (the signal that catches a
regression mid-run).

Bucket-boundary honesty: a histogram only knows bucket edges, so the
error ratio counts as GOOD only observations provably at or under the
largest bucket bound <= ``threshold_s`` — a threshold between edges
rounds conservatively (reports at-least-this-much burn, never less).
Stdlib only, no jax.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_right
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from differential_transformer_replication_tpu.obs.registry import (
    Registry,
)


@dataclass(frozen=True)
class LatencyObjective:
    """``target`` fraction of ``histogram`` observations <= ``threshold_s``."""

    name: str            # objective label, e.g. "ttft"
    histogram: str       # registry histogram name
    threshold_s: float   # latency bound (aligns best with a bucket edge)
    target: float        # e.g. 0.99
    # label selector for a labeled histogram child, e.g.
    # (("priority", "high"),) to read one priority class's ladder from
    # serving_class_ttft_seconds. Empty = the unlabeled histogram.
    labels: Tuple[Tuple[str, str], ...] = ()

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target}"
            )
        if self.threshold_s <= 0:
            raise ValueError(
                f"threshold_s must be > 0, got {self.threshold_s}"
            )


@dataclass(frozen=True)
class AvailabilityObjective:
    """``target`` fraction of outcomes in ``good`` vs ``good``+``bad``
    counters (unlabeled registry counters, summed per side)."""

    name: str
    good: Tuple[str, ...]
    bad: Tuple[str, ...]
    target: float = 0.999

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target}"
            )


# -- the pure math (hand-checkable; tests/test_trace.py drives it) ------


def good_count_under(bounds: Sequence[float],
                     cumulative: Sequence[float],
                     threshold_s: float) -> float:
    """Observations provably <= ``threshold_s``: the cumulative count
    at the largest bucket bound <= the threshold (0 when the threshold
    sits below every bound — nothing is provably fast enough)."""
    i = bisect_right(list(bounds), threshold_s)
    return float(cumulative[i - 1]) if i > 0 else 0.0


def latency_error_ratio(bounds: Sequence[float],
                        cumulative: Sequence[float],
                        count: float,
                        threshold_s: float) -> Optional[float]:
    """Fraction of observations above the threshold; None when the
    histogram is empty (no traffic is not the same as perfect)."""
    if count <= 0:
        return None
    good = good_count_under(bounds, cumulative, threshold_s)
    return max(0.0, (count - good) / count)


def burn_rate(error_ratio: Optional[float],
              target: float) -> Optional[float]:
    """``error_ratio / (1 - target)``; None rides through."""
    if error_ratio is None:
        return None
    budget = 1.0 - target
    if budget <= 0:
        return math.inf if error_ratio > 0 else 0.0
    return error_ratio / budget


def histogram_from_samples(samples, name: str,
                           match: Optional[Dict[str, str]] = None):
    """Rebuild ``(bounds, cumulative, count)`` for one histogram from
    parsed exposition samples (obs/registry.py:parse_exposition) — the
    scrape-side twin of ``Histogram.snapshot`` that
    tools/slo_report.py uses on a saved or fetched /metrics body.
    Samples surviving the ``match`` filter are SUMMED per bucket bound
    across label children, so a labeled histogram (or a fleet body
    whose gauged buckets carry per-replica labels) aggregates to one
    valid histogram instead of interleaving children's ladders —
    sound because cumulative bucket counts are themselves counters."""
    by_bound: Dict[float, float] = {}
    count = 0.0
    for n, labels, value in samples:
        extra = dict(labels)
        le = extra.pop("le", None)
        if match and any(extra.get(k) != v for k, v in match.items()):
            continue
        if n == f"{name}_bucket" and le is not None:
            bound = math.inf if le == "+Inf" else float(le)
            by_bound[bound] = by_bound.get(bound, 0.0) + value
        elif n == f"{name}_count":
            count += value
    bounds = sorted(b for b in by_bound if not math.isinf(b))
    cumulative = [by_bound[b] for b in bounds]
    return bounds, cumulative, count


# -- the live monitor ---------------------------------------------------


@dataclass
class _Window:
    """Previous-evaluation snapshot for windowed burn."""

    good: float = 0.0
    count: float = 0.0


class SLOMonitor:
    """Evaluate objectives against a registry; see module docstring.

    The monitor reads AND writes one registry: objective inputs come
    from the instrumented histograms/counters, results land in
    ``slo_*`` gauges labeled by objective. ``evaluate()`` is cheap
    (a few snapshots) — the serving server runs it on every /metrics
    scrape so the gauges are always current at scrape time.
    """

    def __init__(self, registry: Registry,
                 latency: Sequence[LatencyObjective] = (),
                 availability: Sequence[AvailabilityObjective] = ()):
        self.registry = registry
        self.latency = tuple(latency)
        self.availability = tuple(availability)
        names = [o.name for o in self.latency + self.availability]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        self._windows: Dict[str, _Window] = {
            name: _Window() for name in names
        }
        # evaluate() runs from ThreadingHTTPServer handler threads
        # (every /metrics scrape): the window read-modify-write and the
        # paired gauge publishes must not interleave between two
        # concurrent scrapers
        self._lock = threading.Lock()
        reg = registry
        self._target_gauge = reg.gauge(
            "slo_target",
            "Configured objective target (fraction good).",
            labelnames=("objective",),
        )
        self._threshold_gauge = reg.gauge(
            "slo_latency_threshold_seconds",
            "Configured latency bound per latency objective.",
            labelnames=("objective",),
        )
        self._error_gauge = reg.gauge(
            "slo_error_ratio",
            "Observed lifetime fraction of objective violations.",
            labelnames=("objective",),
        )
        self._burn_gauge = reg.gauge(
            "slo_burn_rate",
            "Lifetime error-budget burn rate (error_ratio / budget; "
            ">1 = the objective is being missed).",
            labelnames=("objective",),
        )
        self._burn_window_gauge = reg.gauge(
            "slo_burn_rate_window",
            "Burn rate over the window since the previous evaluation "
            "(the fast regression signal).",
            labelnames=("objective",),
        )
        for o in self.latency:
            self._target_gauge.set(o.target, objective=o.name)
            self._threshold_gauge.set(o.threshold_s, objective=o.name)
        for o in self.availability:
            self._target_gauge.set(o.target, objective=o.name)

    def _publish(self, name: str, target: float,
                 good: float, count: float) -> dict:
        err = None if count <= 0 else max(0.0, (count - good) / count)
        w = self._windows[name]
        d_count = count - w.count
        d_good = good - w.good
        w_err = (
            None if d_count <= 0
            else max(0.0, (d_count - d_good) / d_count)
        )
        self._windows[name] = _Window(good=good, count=count)
        out = {
            "target": target,
            "count": count,
            "error_ratio": err,
            "burn_rate": burn_rate(err, target),
            "window_count": max(0.0, d_count),
            "window_error_ratio": w_err,
            "window_burn_rate": burn_rate(w_err, target),
        }
        if err is not None:
            self._error_gauge.set(err, objective=name)
            self._burn_gauge.set(out["burn_rate"], objective=name)
        if w_err is not None:
            self._burn_window_gauge.set(
                out["window_burn_rate"], objective=name
            )
        return out

    def evaluate(self) -> Dict[str, dict]:
        """Compute every objective, refresh the ``slo_*`` gauges, and
        return ``{objective: {error_ratio, burn_rate, ...}}``.
        Serialized: concurrent scrapers each get a consistent window
        instead of double-counting (or zero-counting) one interval."""
        with self._lock:
            out: Dict[str, dict] = {}
            for o in self.latency:
                # a labeled objective must re-fetch the histogram with
                # the SAME labelnames tuple it was registered under
                # (the registry enforces one tuple per name forever)
                hist = self.registry.histogram(
                    o.histogram,
                    labelnames=tuple(k for k, _ in o.labels),
                )
                snap = hist.snapshot(**dict(o.labels))
                bounds, cumulative = snap["buckets"], snap["cumulative"]
                good = good_count_under(bounds, cumulative,
                                        o.threshold_s)
                out[o.name] = self._publish(
                    o.name, o.target, good, float(snap["count"])
                )
                out[o.name]["threshold_s"] = o.threshold_s
            for o in self.availability:
                good = sum(
                    self.registry.counter(n).value for n in o.good
                )
                bad = sum(
                    self.registry.counter(n).value for n in o.bad
                )
                out[o.name] = self._publish(
                    o.name, o.target, good, good + bad
                )
            return out


def default_serving_objectives(
    ttft_threshold_s: float = 1.0,
    itl_threshold_s: float = 0.25,
    latency_target: float = 0.99,
    availability_target: float = 0.999,
    priority_classes: Sequence[str] = ("high", "normal", "batch"),
) -> Tuple[List[LatencyObjective], List[AvailabilityObjective]]:
    """The serving stack's stock objectives over the engine's existing
    metrics (serving/engine.py names), used by the server CLI knobs.

    Beyond the aggregate ttft/itl objectives, one TTFT and one ITL
    objective per priority class rides along (over the engine's
    ``serving_class_*`` histograms), so burn rates are visible
    per-class: under KV pressure the whole point of the priority
    scheduler is that "high" keeps its budget while "batch" burns.
    Classes with no traffic report no error ratio (None), so unused
    classes never alarm. Pass ``priority_classes=()`` to disable."""
    latency = [
        LatencyObjective("ttft", "serving_ttft_seconds",
                         ttft_threshold_s, latency_target),
        LatencyObjective("itl", "serving_itl_seconds",
                         itl_threshold_s, latency_target),
    ]
    for cls in priority_classes:
        latency.append(LatencyObjective(
            f"ttft_{cls}", "serving_class_ttft_seconds",
            ttft_threshold_s, latency_target,
            labels=(("priority", cls),),
        ))
        latency.append(LatencyObjective(
            f"itl_{cls}", "serving_class_itl_seconds",
            itl_threshold_s, latency_target,
            labels=(("priority", cls),),
        ))
    availability = [
        AvailabilityObjective(
            "availability",
            good=("serving_requests_completed_total",),
            bad=("serving_requests_rejected_total",
                 "serving_requests_deadline_expired_total"),
            target=availability_target,
        ),
    ]
    return latency, availability
