"""Structured JSONL event log: the fleet's append-only flight record.

The router, the fleet supervisor, and the serving server each grew ad
hoc ``print(..., file=sys.stderr)`` forensics — useful to a human
tailing one process, useless for answering "what happened to request
X" across a fleet. This module unifies them into one machine-readable
shape: one JSON object per line, every record carrying

- ``ts`` — unix wall-clock seconds (joinable across processes),
- ``event`` — a stable snake_case name (``request_finished``,
  ``replica_ejected``, ``rolling_drain``, ...),
- ``process`` — who wrote it (``router`` / ``replica`` / ``fleet``),
- whatever fields the emitter adds — request-scoped events carry
  ``trace_id``, so ``grep trace_id events.jsonl`` and
  ``tools/trace_stitch.py`` tell the same story from two angles.

Same durability posture as obs/spans.py: buffered appends under a
lock, explicit ``flush``/``close`` wired into the graceful-drain and
SIGTERM paths, and an ``atexit`` safety net so an un-drained exit
still lands the buffered tail. Append mode — supervisor relaunches
extend the log rather than truncating the forensics they exist to
explain. Stdlib only; :data:`NOOP_EVENTS` keeps instrumentation sites
branch-free when logging is off.

Size-based rotation (``max_bytes`` > 0): a long-lived fleet must not
grow one unbounded file. Rotation happens at FLUSH boundaries only —
every write is a batch of whole lines, so neither the active file nor
any rotated generation ever ends in a torn line. The cascade is
``events.jsonl`` -> ``.1`` -> ... -> ``.keep`` via atomic
``os.replace`` (the oldest generation falls off); ``keep=0`` just
truncates. A crash between renames leaves at worst a duplicated
generation — never a missing or torn one.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import List, Optional


class EventLog:
    """Append-only JSONL event sink; see module docstring."""

    def __init__(self, path: str, process: str = "",
                 flush_every: int = 64, max_bytes: int = 0,
                 keep: int = 3):
        if max_bytes < 0 or keep < 0:
            raise ValueError(
                f"max_bytes/keep must be >= 0, got {max_bytes}/{keep}"
            )
        self.path = path
        self.process = process
        self.max_bytes = int(max_bytes)  # 0 = rotation off
        self.keep = int(keep)
        self._lock = threading.Lock()
        self._buf: List[str] = []
        self._flush_every = max(1, flush_every)
        self._closed = False
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        atexit.register(self.close)

    def emit(self, event: str, **fields) -> None:
        """Append one record; ``ts`` and ``process`` are added for the
        caller. Non-JSON-serializable field values are stringified —
        a forensic log must never throw back at its emitter."""
        record = {"ts": round(time.time(), 3), "event": event}
        if self.process:
            record["process"] = self.process
        record.update(fields)
        try:
            line = json.dumps(record)
        except (TypeError, ValueError):
            line = json.dumps({
                k: v if isinstance(v, (str, int, float, bool, type(None)))
                else repr(v)
                for k, v in record.items()
            })
        with self._lock:
            if self._closed:
                return
            self._buf.append(line)
            if len(self._buf) >= self._flush_every:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf:
            self._fh.write("\n".join(self._buf) + "\n")
            self._buf.clear()
        if self.max_bytes and self._fh.tell() >= self.max_bytes:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Close, cascade the generations, reopen fresh. Flush-boundary
        only, so every file involved holds whole lines."""
        self._fh.close()
        if self.keep > 0:
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._fh = open(self.path, "a", encoding="utf-8")

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._flush_locked()
                self._fh.flush()

    def close(self) -> None:
        """Flush and close; idempotent (the atexit net double-closes)."""
        with self._lock:
            if self._closed:
                return
            self._flush_locked()
            self._fh.close()
            self._closed = True


class _NoopEventLog:
    """Shared do-nothing sink so emit sites never branch."""

    __slots__ = ()
    path = None
    process = ""

    def emit(self, event: str, **fields) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


NOOP_EVENTS = _NoopEventLog()


def open_event_log(path: Optional[str], process: str = "",
                   max_bytes: int = 0, keep: int = 3):
    """``EventLog`` when a path is given, else the shared no-op — the
    one-liner every CLI flag funnels through. ``max_bytes``/``keep``
    arm size-based rotation (module docstring)."""
    if not path:
        return NOOP_EVENTS
    return EventLog(path, process=process, max_bytes=max_bytes, keep=keep)
