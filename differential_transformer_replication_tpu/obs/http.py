"""Minimal Prometheus exporter: ``GET /metrics`` over stdlib http.server.

The training-side sidecar (``train.py --metrics-port``): one daemon
thread serving a :class:`~.registry.Registry`'s text exposition so a
Prometheus scraper (or ``curl``) can watch a live run without touching
the train loop. The serving server does NOT use this module's server —
it already owns a ThreadingHTTPServer and mounts the same rendering on
its own ``/metrics`` path (serving/server.py) — but shares the
content-type constant so both endpoints stay scrape-compatible.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from differential_transformer_replication_tpu.obs.registry import (
    CONTENT_TYPE,
    Registry,
)


def _make_handler(registry: Registry):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = registry.render().encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet: scrapes every few seconds
            pass

    return Handler


def start_metrics_server(registry: Registry, port: int,
                         host: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Serve ``registry`` at ``http://host:port/metrics`` from a daemon
    thread; returns the server (call ``.shutdown()`` then
    ``.server_close()`` to stop). ``port=0`` binds an ephemeral port —
    read it back from ``server.server_address[1]``."""
    server = ThreadingHTTPServer((host, port), _make_handler(registry))
    thread = threading.Thread(
        target=server.serve_forever, name="metrics-exporter", daemon=True
    )
    thread.start()
    return server
