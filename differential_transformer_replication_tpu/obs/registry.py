"""Thread-safe metrics registry with a Prometheus text-exposition writer.

The smallest useful subset of the Prometheus client model, stdlib-only
(the container must not grow dependencies):

- :class:`Counter` — monotonically increasing float (``inc``). ``set``
  exists for compatibility shims (serving/engine.py's stats mapping
  exposes ``+=`` through it) but instrumented code should ``inc``.
- :class:`Gauge` — settable value with ``set``/``inc``/``dec`` and a
  ``set_max`` watermark helper (device-memory high-water mark).
- :class:`Histogram` — fixed cumulative buckets + sum + count; the
  preset :data:`LATENCY_BUCKETS_S` ladder covers sub-ms sampling ticks
  through multi-minute prefill storms.

Labels: a metric created with ``labelnames`` is a family; calling
``.labels(k=v)`` returns (creating on first use) the child for that
label set. Unlabeled metrics are their own single child.

Every mutation takes the metric's own lock, so concurrent increments
from the engine thread and HTTP handler threads never tear; a
whole-registry snapshot (``render`` / ``snapshot``) takes the registry
lock so the metric SET is stable while iterating (per-child values are
each read atomically — the standard Prometheus consistency level).
"""

from __future__ import annotations

import math
import re
import threading
import time
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# Latency ladder in seconds: 0.5 ms .. 60 s. Wide enough for sampling
# ticks, decode iterations, prefill chunks, and whole train steps.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(text: str) -> str:
    return (
        text.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def _labels_key(
    labelnames: Sequence[str], labels: dict
) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"expected labels {tuple(labelnames)}, got {tuple(labels)}"
        )
    return tuple(str(labels[n]) for n in labelnames)


def _render_labels(labelnames: Sequence[str],
                   values: Sequence[str],
                   extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = [
        f'{n}="{_escape_label_value(v)}"'
        for n, v in zip(labelnames, values)
    ]
    if extra is not None:
        pairs.append(f'{extra[0]}="{_escape_label_value(extra[1])}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Child:
    """One (metric, label-set) time series; scalar value + lock."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _Metric:
    """Common family machinery: child management by label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()) -> None:
        self.name = _check_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            _check_name(ln)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **labels):
        key = _labels_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name} has labels {self.labelnames}; "
                "call .labels(...) first"
            )
        return self._children[()]

    def _items(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())


class Counter(_Metric):
    kind = "counter"

    def _make_child(self) -> _Child:
        return _Child()

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc {amount})")
        child = self.labels(**labels) if labels else self._default()
        with child._lock:
            child._value += amount

    def set(self, value: float, **labels) -> None:
        """Compat shim for mapping-style stats (``stats[k] = v``); only
        monotone assignments make sense for a counter and callers that
        rewind one get what they asked for."""
        child = self.labels(**labels) if labels else self._default()
        with child._lock:
            child._value = float(value)

    @property
    def value(self) -> float:
        return self._default().value

    def render(self, out: List[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} counter")
        for key, child in self._items():
            lbl = _render_labels(self.labelnames, key)
            out.append(f"{self.name}{lbl} {_fmt_value(child.value)}")


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self) -> _Child:
        return _Child()

    def set(self, value: float, **labels) -> None:
        child = self.labels(**labels) if labels else self._default()
        with child._lock:
            child._value = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        child = self.labels(**labels) if labels else self._default()
        with child._lock:
            child._value += amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def set_max(self, value: float, **labels) -> None:
        """Watermark update: keep the max of the current and new value."""
        child = self.labels(**labels) if labels else self._default()
        with child._lock:
            if value > child._value:
                child._value = float(value)

    @property
    def value(self) -> float:
        return self._default().value

    def render(self, out: List[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} gauge")
        for key, child in self._items():
            lbl = _render_labels(self.labelnames, key)
            out.append(f"{self.name}{lbl} {_fmt_value(child.value)}")


class _HistChild:
    __slots__ = ("_lock", "counts", "sum", "count")

    def __init__(self, n_buckets: int) -> None:
        self._lock = threading.Lock()
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = LATENCY_BUCKETS_S) -> None:
        bounds = sorted(float(b) for b in buckets)
        if not bounds or any(
            b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])
        ):
            raise ValueError(f"buckets must be strictly increasing: {buckets}")
        self.buckets = tuple(bounds)  # upper bounds, +Inf implicit
        super().__init__(name, help, labelnames)

    def _make_child(self) -> _HistChild:
        return _HistChild(len(self.buckets) + 1)

    def observe(self, value: float, **labels) -> None:
        child = self.labels(**labels) if labels else self._default()
        i = bisect_left(self.buckets, value)
        with child._lock:
            child.counts[i] += 1
            child.sum += value
            child.count += 1

    def snapshot(self, **labels) -> dict:
        """(cumulative bucket counts, sum, count) for one child."""
        child = self.labels(**labels) if labels else self._default()
        with child._lock:
            counts, total, n = list(child.counts), child.sum, child.count
        cum, acc = [], 0
        for c in counts:
            acc += c
            cum.append(acc)
        return {"buckets": self.buckets, "cumulative": cum,
                "sum": total, "count": n}

    def render(self, out: List[str]) -> None:
        out.append(f"# HELP {self.name} {_escape_help(self.help)}")
        out.append(f"# TYPE {self.name} histogram")
        for key, child in self._items():
            with child._lock:
                counts = list(child.counts)
                total, n = child.sum, child.count
            acc = 0
            for bound, c in zip(self.buckets, counts):
                acc += c
                lbl = _render_labels(
                    self.labelnames, key, extra=("le", _fmt_value(bound))
                )
                out.append(f"{self.name}_bucket{lbl} {acc}")
            lbl = _render_labels(self.labelnames, key, extra=("le", "+Inf"))
            out.append(f"{self.name}_bucket{lbl} {n}")
            lbl = _render_labels(self.labelnames, key)
            out.append(f"{self.name}_sum{lbl} {_fmt_value(total)}")
            out.append(f"{self.name}_count{lbl} {n}")


class Registry:
    """Named metric collection; get-or-create semantics so instrumented
    modules can share one registry without import-order coupling."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or (
                    existing.labelnames != tuple(labelnames)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 (what ``GET /metrics``
        returns; ``promtool check metrics``-clean)."""
        out: List[str] = []
        for metric in self.metrics():
            metric.render(out)
        return "\n".join(out) + "\n" if out else ""


class StatsMap:
    """Dict-compatible view over a fixed set of registry counters.

    Keeps call sites (and the ``/health`` JSON shape) that grew around a
    plain stats dict working — ``stats["completed"]``, ``dict(stats)``,
    ``"rejected" in stats`` — while the authoritative values live in
    Prometheus counters, so the ``/metrics`` exposition and the stats
    snapshot can never disagree. Mutation through :meth:`inc` is atomic
    (the counter's own lock); ``stats[k] = v`` / ``stats[k] += 1`` stay
    supported for compatibility but the read-modify-write of ``+=`` is
    only safe on a single thread (the engine loop) — concurrent writers
    must use :meth:`inc`.
    """

    def __init__(self, registry: "Registry", spec: dict) -> None:
        """``spec``: ordered ``{key: (metric_name, help)}``."""
        self._counters: Dict[str, Counter] = {
            key: registry.counter(name, help)
            for key, (name, help) in spec.items()
        }

    def inc(self, key: str, amount: float = 1.0) -> None:
        self._counters[key].inc(amount)

    def snapshot(self) -> Dict[str, int]:
        """Point-in-time copy; each value read under its counter's
        lock (no torn reads from a mid-increment engine thread)."""
        return {k: int(c.value) for k, c in self._counters.items()}

    # -- mapping compatibility ----------------------------------------

    def __getitem__(self, key: str) -> int:
        return int(self._counters[key].value)

    def __setitem__(self, key: str, value: float) -> None:
        self._counters[key].set(value)

    def __contains__(self, key: str) -> bool:
        return key in self._counters

    def __iter__(self):
        return iter(self._counters)

    def __len__(self) -> int:
        return len(self._counters)

    def keys(self):
        return self._counters.keys()

    def items(self):
        return [(k, int(c.value)) for k, c in self._counters.items()]

    def __repr__(self) -> str:
        return f"StatsMap({self.snapshot()!r})"


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- reading expositions back (the scrape side) -------------------------
#
# The router aggregates its replicas' /metrics bodies into one fleet
# exposition, and tools/slo_report.py computes burn rates from a
# scraped snapshot — both need to PARSE the format this module writes.
# One canonical parser here keeps writer and reader in lockstep.

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(text: str) -> str:
    out, i = [], 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            nxt = text[i + 1]
            out.append("\n" if nxt == "n" else nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(raw: str) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    return float(raw)  # "NaN" parses natively


def parse_exposition(
    text: str,
) -> Tuple[Dict[str, str], List[Tuple[str, Dict[str, str], float]]]:
    """Parse a text exposition into ``(types, samples)``:
    ``types[name] = kind`` from ``# TYPE`` lines, ``samples`` a list of
    ``(sample_name, labels, value)``. Malformed lines are skipped —
    a scrape of a foreign (or half-written) endpoint must degrade to
    partial data, not an exception."""
    types: Dict[str, str] = {}
    samples: List[Tuple[str, Dict[str, str], float]] = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) == 4:
                types[parts[2]] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        labels: Dict[str, str] = {}
        if m.group(2):
            for lm in _LABEL_RE.finditer(m.group(2)):
                labels[lm.group(1)] = _unescape_label_value(lm.group(2))
        try:
            samples.append((m.group(1), labels, _parse_value(m.group(3))))
        except ValueError:
            continue
    return types, samples


def set_build_info(registry: "Registry", role: str,
                   config_hash: str = "",
                   version: Optional[str] = None,
                   start_time: Optional[float] = None) -> None:
    """Stamp a registry with process identity: a ``build_info`` info
    gauge (constant 1; the identity rides the labels, the standard
    Prometheus idiom) plus ``process_start_time_seconds``. With these,
    an aggregated fleet scrape (router ``/fleet/metrics``) can tell a
    router from a replica from a trainer, spot config drift between
    replicas, and detect silent restarts (start time moved).

    ``role`` is ``router`` | ``replica`` | ``trainer``. ``version`` is
    the jax version; resolved from package metadata when omitted —
    WITHOUT importing jax, so the stdlib-only router can stamp itself.
    """
    if version is None:
        try:
            from importlib.metadata import version as _pkg_version

            version = _pkg_version("jax")
        except Exception:
            version = "unknown"
    registry.gauge(
        "build_info",
        "Process identity (constant 1; role/config/version in labels).",
        labelnames=("role", "config_hash", "jax_version"),
    ).set(1, role=role, config_hash=config_hash, jax_version=version)
    registry.gauge(
        "process_start_time_seconds",
        "Unix time this process's registry was stamped (a moved value "
        "across scrapes of one target means a restart).",
    ).set(time.time() if start_time is None else start_time)
