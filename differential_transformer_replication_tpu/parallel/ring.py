"""Ring attention: sequence/context parallelism over the mesh.

The reference caps sequence length at a dense-masked block_size=512
(train.py:63) and has no distributed machinery at all (SURVEY.md section
5.7-5.8). This module is the TPU-native long-context path: the sequence
dim is sharded over the mesh's ``sequence`` axis, each device keeps its
local Q shard, and K/V shards rotate around the ring via
``jax.lax.ppermute`` — P steps of blockwise attention with an
online-softmax accumulator, so no device ever holds the full sequence or
any (T, T) map. Collectives ride ICI; compute overlaps the rotation.

Like ops/flash.py, one implementation serves all three model families via
the multi-stream form: ``out = sum_s coeff[s,h] * softmax_s @ V``.

The op is wrapped in ``shard_map`` whose in_specs compose with the other
mesh axes: batch stays on ``data``/``fsdp``, heads stay on ``tensor``,
sequence is the ring axis. Everything outside attention (RoPE tables,
position embeddings, LayerNorm, FFN, loss) remains under automatic GSPMD
partitioning — attention is the only op whose sharding XLA cannot infer
profitably, because causal blockwise structure is a manual schedule.

Autodiff: ``ppermute`` transposes to ``ppermute``, so ``jax.grad``
through the ring gives the standard ring-attention backward (KV grads
rotate back around the ring).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from differential_transformer_replication_tpu.ops.flash import (
    auto_interpret,
    flash_chunk_attention,
    pick_block,
)
from differential_transformer_replication_tpu.ops.streams import (
    NEG_INF,
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)

_BATCH_AXES = ("data", "fsdp")
_SEQ_AXIS = "sequence"
_HEAD_AXIS = "tensor"


def _ring_flash_body(
    qs: jnp.ndarray,  # (S, Bl, Tl, Hl, d) local shard
    ks: jnp.ndarray,  # (S, Bl, Tl, Hl, d)
    v: jnp.ndarray,  # (Bl, Tl, Hl, dv)
    coeffs: jnp.ndarray,  # (S, Hl) float32
) -> jnp.ndarray:
    """Ring body whose per-chunk compute is the fused Pallas chunk kernel
    (ops/flash.py:flash_chunk_attention) — no Tl x Tl map is materialized
    even chunk-locally. Chunks merge exactly via the running logsumexp
    recurrence: with per-chunk normalized outputs o_c and logsumexps
    lse_c, ``lse' = logaddexp(lse, lse_c)`` and
    ``o' = o*exp(lse-lse') + o_c*exp(lse_c-lse')``."""
    S, B, Tl, H, d = qs.shape
    dv = v.shape[-1]
    p = jax.lax.axis_size(_SEQ_AXIS)
    my = jax.lax.axis_index(_SEQ_AXIS)
    interpret = auto_interpret()
    bq = pick_block(128, Tl)
    bk = pick_block(128, Tl)
    blocks = (bq, bk, bq, bk)

    # (S, B, Tl, H, d) -> (B*H, S, Tl, d)
    q_r = qs.transpose(1, 3, 0, 2, 4).reshape(B * H, S, Tl, d)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        o, lse, ks_t, v_t = carry
        src = jax.lax.rem(my - t + p, p)
        off = ((my - src) * Tl).astype(jnp.float32).reshape(1, 1)
        k_r = ks_t.transpose(1, 3, 0, 2, 4).reshape(B * H, S, Tl, d)
        v_r = v_t.transpose(0, 2, 1, 3).reshape(B * H, Tl, dv)
        o_c, lse_c = flash_chunk_attention(q_r, k_r, v_r, off, blocks, interpret)
        lse_new = jnp.logaddexp(lse, lse_c)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_new = jnp.exp(lse_c - lse_new)[..., None]
        o_new = o * w_old + o_c.astype(jnp.float32) * w_new
        ks_n = jax.lax.ppermute(ks_t, _SEQ_AXIS, perm)
        v_n = jax.lax.ppermute(v_t, _SEQ_AXIS, perm)
        return o_new, lse_new, ks_n, v_n

    o0 = jnp.zeros((B * H, S, Tl, dv), jnp.float32)
    lse0 = jnp.full((B * H, S, Tl), NEG_INF, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(0, p, step, (o0, lse0, ks, v))

    # combine streams with the per-head coefficients, back to (B, Tl, H, dv)
    o_bh = o.reshape(B, H, S, Tl, dv)
    out = jnp.einsum("sh,bhstd->bhtd", coeffs.astype(jnp.float32), o_bh)
    return out.transpose(0, 2, 1, 3).astype(v.dtype)


def _ring_shard_body(
    qs: jnp.ndarray,  # (S, Bl, Tl, Hl, d) local shard
    ks: jnp.ndarray,  # (S, Bl, Tl, Hl, d)
    v: jnp.ndarray,  # (Bl, Tl, Hl, dv)
    coeffs: jnp.ndarray,  # (S, Hl) float32
) -> jnp.ndarray:
    """Runs on each device inside shard_map. Rotates (ks, v) around the
    ``sequence`` ring; accumulates S online-softmax streams against the
    local Q shard."""
    S, B, Tl, H, d = qs.shape
    dv = v.shape[-1]
    p = jax.lax.axis_size(_SEQ_AXIS)
    my = jax.lax.axis_index(_SEQ_AXIS)
    scale = 1.0 / math.sqrt(d)

    q32 = qs.astype(jnp.float32)
    rows = my * Tl + jax.lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        m, l, acc, ks_t, v_t = carry
        # after t rotations this device holds the KV shard of ring position
        # (my - t) mod p
        src = jax.lax.rem(my - t + p, p)
        k32 = ks_t.astype(jnp.float32)
        s = jnp.einsum("sbthd,sbuhd->sbhtu", q32, k32) * scale
        cols = src * Tl + jax.lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
        s = jnp.where((cols <= rows)[None, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        pr = jnp.exp(s - m_new[..., None])  # (S, B, H, Tl, Tl)
        l_new = l * alpha + jnp.sum(pr, axis=-1)
        pv = jnp.einsum("sbhtu,buhe->sbhte", pr, v_t.astype(jnp.float32))
        acc_new = acc * alpha[..., None] + pv
        # rotate KV to the next device; the last step's rotation restores
        # the original placement (and keeps every step's collective uniform)
        ks_n = jax.lax.ppermute(ks_t, _SEQ_AXIS, perm)
        v_n = jax.lax.ppermute(v_t, _SEQ_AXIS, perm)
        return m_new, l_new, acc_new, ks_n, v_n

    m0 = jnp.full((S, B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((S, B, H, Tl), jnp.float32)
    a0 = jnp.zeros((S, B, H, Tl, dv), jnp.float32)
    m, l, acc, _, _ = jax.lax.fori_loop(0, p, step, (m0, l0, a0, ks, v))

    # step 0 visits the local diagonal chunk, so l > 0 everywhere
    o_s = acc / l[..., None]  # (S, B, H, Tl, dv)
    out = jnp.einsum("sh,sbhte->bhte", coeffs.astype(jnp.float32), o_s)
    return out.transpose(0, 2, 1, 3).astype(v.dtype)  # (Bl, Tl, Hl, dv)


def ring_multi_stream_attention(
    qs: jnp.ndarray,  # (S, B, T, H, d) global
    ks: jnp.ndarray,
    v: jnp.ndarray,  # (B, T, H, dv) global
    coeffs: jnp.ndarray,  # (S, H) float32
    mesh: Mesh,
    impl: str = "xla",
) -> jnp.ndarray:
    """Causal multi-stream attention with the sequence dim ring-sharded
    over ``mesh``'s ``sequence`` axis. Global shapes in, global out —
    callable from inside an outer jit; composes with data/fsdp batch
    sharding and tensor head sharding.

    ``impl``: "xla" computes each chunk with dense masked softmax (Tl x Tl
    chunk-local maps); "pallas" runs the fused flash chunk kernel inside
    the ring, so even chunk-local memory stays O(Tl) — ring flash
    attention, the long-context configuration."""
    qk_spec = P(None, _BATCH_AXES, _SEQ_AXIS, _HEAD_AXIS, None)
    v_spec = P(_BATCH_AXES, _SEQ_AXIS, _HEAD_AXIS, None)
    c_spec = P(None, _HEAD_AXIS)
    body = _ring_flash_body if impl == "pallas" else _ring_shard_body
    inner = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(qk_spec, qk_spec, v_spec, c_spec),
        out_specs=v_spec,
        check_vma=False,
    )
    return inner(qs, ks, v, coeffs)


def ring_vanilla_attention(q, k, v, mesh: Mesh, impl: str = "xla"):
    """Sequence-parallel form of ops.attention.vanilla_attention."""
    return ring_multi_stream_attention(
        q[None], k[None], v, vanilla_coeffs(q.shape[2]), mesh, impl
    )


def ring_diff_attention(q1, k1, q2, k2, v, lam, mesh: Mesh, impl: str = "xla"):
    """Sequence-parallel form of ops.attention.diff_attention:
    coeffs [1, -lambda] (diff_transformer.py:70)."""
    qs = jnp.stack([q1, q2])
    ks = jnp.stack([k1, k2])
    return ring_multi_stream_attention(qs, ks, v, diff_coeffs(lam), mesh, impl)


def ring_ndiff_attention(qs, ks, v, lams, signs, mesh: Mesh, impl: str = "xla"):
    """Sequence-parallel form of ops.attention.ndiff_attention: coeffs
    sign_s * lambda_{s,h} (Ndiff_transformer.py:119-123)."""
    return ring_multi_stream_attention(
        qs, ks, v, ndiff_coeffs(lams, signs), mesh, impl
    )


def use_ring(mesh: Optional[Mesh]) -> bool:
    """Ring attention applies when a mesh with a >1 sequence axis is
    threaded into the forward."""
    return mesh is not None and mesh.shape.get(_SEQ_AXIS, 1) > 1


def check_ring_dropout(dropout_rate: float, rng) -> None:
    """The ring path does not implement attention-prob dropout (like the
    flash kernel, SURVEY.md section 7.7) — but unlike flash there is no
    dense fallback that preserves the sequence sharding, so training with
    active dropout on a sequence-parallel mesh must fail loudly instead
    of silently dropping the regularizer. Both args are trace-static."""
    if dropout_rate > 0.0 and rng is not None:
        raise NotImplementedError(
            "attention-prob dropout is not supported on the sequence-"
            "parallel ring path; train with dropout=0.0 (the reference "
            "default, train.py:64) or a sequence=1 mesh"
        )
