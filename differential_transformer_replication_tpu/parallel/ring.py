"""Ring attention: sequence/context parallelism over the mesh.

The reference caps sequence length at a dense-masked block_size=512
(train.py:63) and has no distributed machinery at all (SURVEY.md section
5.7-5.8). This module is the TPU-native long-context path: the sequence
dim is sharded over the mesh's ``sequence`` axis, each device keeps its
local Q shard, and K/V shards rotate around the ring via
``jax.lax.ppermute`` — P steps of blockwise attention with an
online-softmax accumulator, so no device ever holds the full sequence or
any (T, T) map. Collectives ride ICI; compute overlaps the rotation.

Like ops/flash.py, one implementation serves all three model families via
the multi-stream form: ``out = sum_s coeff[s,h] * softmax_s @ V``.

The op is wrapped in ``shard_map`` whose in_specs compose with the other
mesh axes: batch stays on ``data``/``fsdp``, heads stay on ``tensor``,
sequence is the ring axis. Everything outside attention (RoPE tables,
position embeddings, LayerNorm, FFN, loss) remains under automatic GSPMD
partitioning — attention is the only op whose sharding XLA cannot infer
profitably, because causal blockwise structure is a manual schedule.

Autodiff: ``ppermute`` transposes to ``ppermute``, so ``jax.grad``
through the ring gives the standard ring-attention backward (KV grads
rotate back around the ring).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from differential_transformer_replication_tpu.ops.flash import (
    auto_interpret,
    dropout_seed_from_rng,
    flash_chunk_attention,
    pick_block,
)
from differential_transformer_replication_tpu.ops.streams import (
    NEG_INF,
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)
from differential_transformer_replication_tpu.utils.compat import (
    axis_size as _axis_size,
    shard_map as _shard_map,
)

_BATCH_AXES = ("data", "fsdp")
_SEQ_AXIS = "sequence"
_HEAD_AXIS = "tensor"


def _ring_flash_body(
    qs: jnp.ndarray,  # (S, Bl, Tl, Hl, d) local shard
    ks: jnp.ndarray,  # (S, Bl, Tl, Hl, d)
    v: jnp.ndarray,  # (Bl, Tl, Hl, dv)
    coeffs: jnp.ndarray,  # (S, Hl) float32
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jnp.ndarray:
    """Ring body whose per-chunk compute is the fused Pallas chunk kernel
    (ops/flash.py:flash_chunk_attention) — no Tl x Tl map is materialized
    even chunk-locally. Chunks merge exactly via the running logsumexp
    recurrence: with per-chunk normalized outputs o_c and logsumexps
    lse_c, ``lse' = logaddexp(lse, lse_c)`` and
    ``o' = o*exp(lse-lse') + o_c*exp(lse_c-lse')``.

    Dropout composes: each chunk drops its probabilities in-kernel after
    local normalization, the lse carries the UNdropped sums, and the
    merge re-weights exactly as in the dropout-free case — globally
    softmax-then-dropout. Masks hash (row, col - off), unique per (q, k)
    pair across the rotation; the caller folds the mesh position into
    the rng so shards decorrelate."""
    S, B, Tl, H, d = qs.shape
    dv = v.shape[-1]
    p = _axis_size(_SEQ_AXIS)
    my = jax.lax.axis_index(_SEQ_AXIS)
    interpret = auto_interpret()
    bq = pick_block(128, Tl)
    bk = pick_block(128, Tl)
    blocks = (bq, bk, bq, bk)
    use_drop = dropout_rate > 0.0 and dropout_rng is not None
    rate = float(dropout_rate) if use_drop else 0.0
    seed = (
        dropout_seed_from_rng(dropout_rng)
        if use_drop
        else jnp.zeros((1, 2), jnp.float32)
    )

    # (S, B, Tl, H, d) -> (B*H, S, Tl, d)
    q_r = qs.transpose(1, 3, 0, 2, 4).reshape(B * H, S, Tl, d)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        o, lse, ks_t, v_t = carry
        src = jax.lax.rem(my - t + p, p)
        off = ((my - src) * Tl).astype(jnp.float32).reshape(1, 1)
        k_r = ks_t.transpose(1, 3, 0, 2, 4).reshape(B * H, S, Tl, d)
        v_r = v_t.transpose(0, 2, 1, 3).reshape(B * H, Tl, dv)
        o_c, lse_c = flash_chunk_attention(
            q_r, k_r, v_r, off, seed, blocks, interpret, rate
        )
        lse_new = jnp.logaddexp(lse, lse_c)
        w_old = jnp.exp(lse - lse_new)[..., None]
        w_new = jnp.exp(lse_c - lse_new)[..., None]
        o_new = o * w_old + o_c.astype(jnp.float32) * w_new
        ks_n = jax.lax.ppermute(ks_t, _SEQ_AXIS, perm)
        v_n = jax.lax.ppermute(v_t, _SEQ_AXIS, perm)
        return o_new, lse_new, ks_n, v_n

    o0 = jnp.zeros((B * H, S, Tl, dv), jnp.float32)
    lse0 = jnp.full((B * H, S, Tl), NEG_INF, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(0, p, step, (o0, lse0, ks, v))

    # combine streams with the per-head coefficients, back to (B, Tl, H, dv)
    o_bh = o.reshape(B, H, S, Tl, dv)
    out = jnp.einsum("sh,bhstd->bhtd", coeffs.astype(jnp.float32), o_bh)
    return out.transpose(0, 2, 1, 3).astype(v.dtype)


def _ring_shard_body(
    qs: jnp.ndarray,  # (S, Bl, Tl, Hl, d) local shard
    ks: jnp.ndarray,  # (S, Bl, Tl, Hl, d)
    v: jnp.ndarray,  # (Bl, Tl, Hl, dv)
    coeffs: jnp.ndarray,  # (S, Hl) float32
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jnp.ndarray:
    """Runs on each device inside shard_map. Rotates (ks, v) around the
    ``sequence`` ring; accumulates S online-softmax streams against the
    local Q shard. Dropout (when a key is given) is applied to each
    step's probabilities before the PV accumulation while the normalizer
    keeps the undropped sums — softmax-then-dropout semantics globally;
    autodiff handles the backward (no mask regeneration needed on this
    dense path)."""
    S, B, Tl, H, d = qs.shape
    dv = v.shape[-1]
    p = _axis_size(_SEQ_AXIS)
    my = jax.lax.axis_index(_SEQ_AXIS)
    scale = 1.0 / math.sqrt(d)
    use_drop = dropout_rate > 0.0 and dropout_rng is not None

    q32 = qs.astype(jnp.float32)
    rows = my * Tl + jax.lax.broadcasted_iota(jnp.int32, (Tl, Tl), 0)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def step(t, carry):
        m, l, acc, ks_t, v_t = carry
        # after t rotations this device holds the KV shard of ring position
        # (my - t) mod p
        src = jax.lax.rem(my - t + p, p)
        k32 = ks_t.astype(jnp.float32)
        s = jnp.einsum("sbthd,sbuhd->sbhtu", q32, k32) * scale
        cols = src * Tl + jax.lax.broadcasted_iota(jnp.int32, (Tl, Tl), 1)
        s = jnp.where((cols <= rows)[None, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        pr = jnp.exp(s - m_new[..., None])  # (S, B, H, Tl, Tl)
        l_new = l * alpha + jnp.sum(pr, axis=-1)  # UNdropped normalizer
        pr_pv = pr
        if use_drop:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_rng, t), 1.0 - dropout_rate,
                pr.shape,
            )
            pr_pv = jnp.where(keep, pr / (1.0 - dropout_rate), 0.0)
        pv = jnp.einsum("sbhtu,buhe->sbhte", pr_pv, v_t.astype(jnp.float32))
        acc_new = acc * alpha[..., None] + pv
        # rotate KV to the next device; the last step's rotation restores
        # the original placement (and keeps every step's collective uniform)
        ks_n = jax.lax.ppermute(ks_t, _SEQ_AXIS, perm)
        v_n = jax.lax.ppermute(v_t, _SEQ_AXIS, perm)
        return m_new, l_new, acc_new, ks_n, v_n

    m0 = jnp.full((S, B, H, Tl), NEG_INF, jnp.float32)
    l0 = jnp.zeros((S, B, H, Tl), jnp.float32)
    a0 = jnp.zeros((S, B, H, Tl, dv), jnp.float32)
    m, l, acc, _, _ = jax.lax.fori_loop(0, p, step, (m0, l0, a0, ks, v))

    # step 0 visits the local diagonal chunk, so l > 0 everywhere
    o_s = acc / l[..., None]  # (S, B, H, Tl, dv)
    out = jnp.einsum("sh,sbhte->bhte", coeffs.astype(jnp.float32), o_s)
    return out.transpose(0, 2, 1, 3).astype(v.dtype)  # (Bl, Tl, Hl, dv)


def sequence_shard_map(body, mesh: Mesh, qs, ks, v, coeffs, *, dropout_rng=None):
    """The shard_map scaffolding SHARED by both sequence-parallel
    strategies (ring here, all-to-all in parallel/ulysses.py): batch over
    data/fsdp, T over ``sequence``, heads over ``tensor``; ``body`` is
    ``(qs_l, ks_l, v_l, coeffs_l, rng) -> out_l``. With a key, the
    replicated rng is folded with the device's FULL mesh position before
    reaching body — the fold that keeps every shard's dropout masks
    independent; keeping it in one place keeps the two strategies'
    dropout semantics from drifting."""
    qk_spec = P(None, _BATCH_AXES, _SEQ_AXIS, _HEAD_AXIS, None)
    v_spec = P(_BATCH_AXES, _SEQ_AXIS, _HEAD_AXIS, None)
    c_spec = P(None, _HEAD_AXIS)

    if dropout_rng is not None:
        def folded(qs_l, ks_l, v_l, c_l, rng):
            pos = jax.lax.axis_index(_BATCH_AXES[0])
            for ax in (_BATCH_AXES[1], _HEAD_AXIS, _SEQ_AXIS):
                pos = pos * mesh.shape[ax] + jax.lax.axis_index(ax)
            return body(qs_l, ks_l, v_l, c_l, jax.random.fold_in(rng, pos))

        inner = _shard_map(
            folded,
            mesh=mesh,
            in_specs=(qk_spec, qk_spec, v_spec, c_spec, P()),
            out_specs=v_spec,
            check_vma=False,
        )
        return inner(qs, ks, v, coeffs, dropout_rng)

    inner = _shard_map(
        lambda a, b, c, d: body(a, b, c, d, None),
        mesh=mesh,
        in_specs=(qk_spec, qk_spec, v_spec, c_spec),
        out_specs=v_spec,
        check_vma=False,
    )
    return inner(qs, ks, v, coeffs)


def ring_multi_stream_attention(
    qs: jnp.ndarray,  # (S, B, T, H, d) global
    ks: jnp.ndarray,
    v: jnp.ndarray,  # (B, T, H, dv) global
    coeffs: jnp.ndarray,  # (S, H) float32
    mesh: Mesh,
    impl: str = "xla",
    *,
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jnp.ndarray:
    """Causal multi-stream attention with the sequence dim ring-sharded
    over ``mesh``'s ``sequence`` axis. Global shapes in, global out —
    callable from inside an outer jit; composes with data/fsdp batch
    sharding and tensor head sharding.

    ``impl``: "xla" computes each chunk with dense masked softmax (Tl x Tl
    chunk-local maps); "pallas" runs the fused flash chunk kernel inside
    the ring, so even chunk-local memory stays O(Tl) — ring flash
    attention, the long-context configuration.

    With ``dropout_rate`` > 0 and a key, attention-prob dropout is live
    on both impls (each map dropped after normalization, inverted
    scaling); the replicated key is folded with the device's full mesh
    position inside the body so every shard draws independent masks."""
    body_fn = _ring_flash_body if impl == "pallas" else _ring_shard_body
    use_drop = dropout_rate > 0.0 and dropout_rng is not None
    return sequence_shard_map(
        lambda a, b, c, d, rng: body_fn(a, b, c, d, dropout_rate, rng),
        mesh, qs, ks, v, coeffs,
        dropout_rng=dropout_rng if use_drop else None,
    )


def ring_vanilla_attention(q, k, v, mesh: Mesh, impl: str = "xla", **kw):
    """Sequence-parallel form of ops.attention.vanilla_attention."""
    return ring_multi_stream_attention(
        q[None], k[None], v, vanilla_coeffs(q.shape[2]), mesh, impl, **kw
    )


def ring_diff_attention(
    q1, k1, q2, k2, v, lam, mesh: Mesh, impl: str = "xla", **kw
):
    """Sequence-parallel form of ops.attention.diff_attention:
    coeffs [1, -lambda] (diff_transformer.py:70)."""
    qs = jnp.stack([q1, q2])
    ks = jnp.stack([k1, k2])
    return ring_multi_stream_attention(
        qs, ks, v, diff_coeffs(lam), mesh, impl, **kw
    )


def ring_ndiff_attention(
    qs, ks, v, lams, signs, mesh: Mesh, impl: str = "xla", **kw
):
    """Sequence-parallel form of ops.attention.ndiff_attention: coeffs
    sign_s * lambda_{s,h} (Ndiff_transformer.py:119-123)."""
    return ring_multi_stream_attention(
        qs, ks, v, ndiff_coeffs(lams, signs), mesh, impl, **kw
    )


def use_ring(mesh: Optional[Mesh]) -> bool:
    """Ring attention applies when a mesh with a >1 sequence axis is
    threaded into the forward."""
    return mesh is not None and mesh.shape.get(_SEQ_AXIS, 1) > 1
