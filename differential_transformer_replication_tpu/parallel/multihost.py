"""Multi-host (multi-process) distributed runtime.

The reference imports torch.distributed + DDP + DistributedSampler and a
``backend='nccl'`` config field but never initializes any of it
(train.py:7-10, 88; SURVEY.md section 2.3). This module is the working
TPU-native replacement:

  - ``initialize()`` wraps ``jax.distributed.initialize``. On TPU pods
    JAX autodetects coordinator/process topology from the environment; on
    manual clusters pass the coordinator address/count/id explicitly.
    Gradient/parameter collectives then ride ICI within a slice and DCN
    across slices — placement follows the mesh axes (parallel/mesh.py),
    no NCCL-style process-group plumbing.
  - ``global_batch()`` assembles each host's locally drawn windows into
    one global jax.Array laid out per the batch sharding — the working
    replacement for the reference's unused ``DistributedSampler``
    (per-host disjoint draws come free from the epoch permutation:
    each host takes a distinct slice of the same seeded bijection,
    data/native.py).
  - ``is_primary()`` gates logging and checkpoint writes to process 0.

Single-process behavior is identity (no initialization needed), so the
same trainer code runs on a laptop, one chip, or a pod.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the JAX distributed runtime (DCN coordination layer).

    No-op when running single-process with no explicit arguments — the
    common laptop/single-chip case needs no coordinator. On TPU pods all
    three arguments autodetect from the environment when left None."""
    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and already():
        return
    if (
        coordinator_address is None
        and num_processes is None
        and process_id is None
        and jax.process_count() == 1
    ):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_primary() -> bool:
    """True on the process that should write logs/checkpoints."""
    return jax.process_index() == 0


def process_count() -> int:
    return jax.process_count()


def local_batch_slice(global_batch_size: int) -> tuple:
    """(start, size) of this host's share of a global batch — each host
    draws only its own windows (the DistributedSampler capability,
    train.py:8-10, done with arithmetic instead of a sampler object)."""
    n = jax.process_count()
    if global_batch_size % n:
        raise ValueError(
            f"global batch {global_batch_size} must divide evenly over "
            f"{n} processes"
        )
    per = global_batch_size // n
    return jax.process_index() * per, per


def gather_to_host(tree):
    """Host (numpy) copy of a state pytree whose leaves may be sharded
    over NON-addressable devices (fsdp/tensor shards living on other
    processes' chips) — the multi-process-safe replacement for
    ``jax.device_get(state)``, which raises on such arrays.

    On multi-process runs this is a COLLECTIVE: every process must call
    it (each contributes its shards to the allgather), even though only
    the primary typically consumes the result. Single-process it
    degrades to a plain ``device_get``. Fully-replicated leaves (step
    counters, schedules) are read from a local replica without any
    cross-process traffic."""
    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def leaf(x):
        if isinstance(x, jax.Array):
            if x.is_fully_replicated:
                return np.asarray(x)
            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return x

    return jax.tree_util.tree_map(leaf, tree)


def global_batch(local: dict, mesh: Mesh) -> dict:
    """Assemble per-host ``{"x": (A, B_local, T), "y": ...}`` numpy arrays
    into global jax.Arrays sharded per the training batch spec. Each host
    provides only its local shard; no host ever materializes the global
    batch."""
    spec = P(None, ("data", "fsdp"), "sequence")
    sharding = NamedSharding(mesh, spec)
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in local.items()
    }
