"""Device mesh construction.

The reference names NCCL in a config field but never initializes any
distributed machinery (train.py:7-10, 88 — imports with zero call sites).
This module is the TPU-native replacement: a ``jax.sharding.Mesh`` whose
axes map onto ICI, with XLA inserting the collectives (psum gradient
all-reduce for data parallelism, all-gather/reduce-scatter for tensor
parallelism) that DDP+NCCL would have provided.

Axes:
  - ``data``: batch sharding; gradients all-reduced across it,
  - ``fsdp``: parameter/optimizer sharding (a second data-like axis),
  - ``tensor``: head/FFN-hidden/vocab sharding (Megatron-style),
  - ``sequence``: context parallelism (ring attention over sequence),
  - ``pipeline``: GPipe stages (parallel/pipeline.py) — last so
    consecutive stages are adjacent in device-enumeration order.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from differential_transformer_replication_tpu.config import MeshConfig


def create_mesh(cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if cfg.n_devices > len(devices):
        raise ValueError(
            f"mesh shape {cfg.shape} needs {cfg.n_devices} devices, "
            f"got {len(devices)}"
        )
    devices = devices[: cfg.n_devices]  # a smaller mesh uses a device prefix
    arr = np.asarray(devices).reshape(cfg.shape)
    return Mesh(arr, cfg.axis_names)


def single_device_mesh() -> Mesh:
    """An all-ones mesh over the default device — lets the same sharded
    code paths run unmodified on one chip."""
    return create_mesh(MeshConfig(), devices=jax.devices()[:1])
