"""The sharded (multi-chip) training step.

Two placements behind one ``make_sharded_train_step`` entry point:

1. **GSPMD** (the general path): ``jax.jit`` with explicit in/out
   shardings over a Mesh — the partitioner inserts the gradient psum
   over the ``data``/``fsdp`` axes and the tensor-parallel
   all-gathers/reduce-scatters implied by the param specs. This is the
   working replacement for the reference's imported-but-never-used
   DDP/NCCL stack (train.py:7-10, 88).

2. **Overlap-scheduled DP** (pure data-parallel meshes, on by default
   via ``TrainConfig.dp_overlap``): the same step body under
   ``shard_map``, with the gradient all-reduce issued PER LAYER-GROUP
   BUCKET from inside the backward pass. GSPMD emits ONE fused
   all-reduce after the whole backward — at the recipe scale that is
   ~378 MB of gradients fully exposed after the last FLOP. Here each
   bucket's params pass through a custom-vjp identity whose backward is
   ``lax.pmean`` over the data axis, so layer k's all-reduce is issued
   the moment layer k's cotangents exist and XLA's latency-hiding
   scheduler overlaps it with the backward compute of layers < k.
   Bucketing is ``TrainConfig.dp_bucket_layers`` consecutive blocks per
   collective (embeddings and the ln_f/lm_head tail ride their own
   buckets, issued last/first respectively). With gradient accumulation
   (``grad_acc_steps > 1``) the microbatch scan instead accumulates
   LOCAL grads and one whole-tree pmean runs after it — the in-backward
   bucket schedule would re-issue every collective per microbatch (A x
   the volume) with nothing left to overlap. Numerically it is the same
   mean gradient modulo float reduction order (parity-tested against
   the single-device step, accumulated and not), and it stays ONE
   jitted program with a donated state — the zero-recompile pin holds
   (tests/test_fused_ffn.py).

The step body is IDENTICAL to the single-device one (train/step.py);
only the placement differs. That is the point of the SPMD design: one
program, any mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from differential_transformer_replication_tpu.config import TrainConfig
from differential_transformer_replication_tpu.parallel.sharding import (
    batch_sharding,
    state_sharding,
)
from differential_transformer_replication_tpu.train.step import (
    create_train_state,
    make_step_fn,
)
from differential_transformer_replication_tpu.utils import faults
from differential_transformer_replication_tpu.utils.compat import shard_map


def _attach_compile_counter(step, jitted, label: str):
    """Expose a compile-event counter on the step wrapper for the
    trainer's obs layer (``train_compile_events_total``).

    Primary source: the jit's private ``_cache_size`` (compile-cache
    entries; steady state must hold at 1). That attribute is not API —
    on jax versions where it is absent the trainer's counter would
    silently report NOTHING, so fall back to the backend-compile
    monitoring the RecompileSentinel rides (analysis/sanitizers.py:
    ``compile_count``, one event per real XLA backend compilation,
    process-wide). The semantics differ (cache entries vs cumulative
    compiles) but the property the pins watch — the count must stop
    growing at steady state — is the same. Which source is active is
    logged once at build so a drifted jax version is visible in the
    run log, not just as a changed metric baseline.
    """
    cache_size = getattr(jitted, "_cache_size", None)
    if cache_size is not None:
        step._cache_size = cache_size
        step._compile_counter_source = "jit-cache"
    else:
        from differential_transformer_replication_tpu.analysis.sanitizers import (
            compile_count,
        )

        step._cache_size = compile_count
        step._compile_counter_source = "backend-compile-monitor"
    from differential_transformer_replication_tpu.parallel.multihost import (
        is_primary,
    )

    if is_primary():
        print(
            f"[dp_step] {label}: compile-event source = "
            f"{step._compile_counter_source}"
        )
    return step


# ---------------------------------------------------------------------------
# Overlap-scheduled pure-DP path
# ---------------------------------------------------------------------------


def overlap_eligible(cfg: TrainConfig) -> bool:
    """The bucketed-pmean path covers pure data parallelism only: fsdp
    shards the params themselves (replicated P() specs would be wrong)
    and tensor/sequence/pipeline need the partitioner's per-op
    collectives. Those meshes keep the GSPMD path."""
    m = cfg.mesh
    return (
        cfg.dp_overlap
        and m.data > 1
        and m.fsdp == 1
        and m.tensor == 1
        and m.sequence == 1
        and m.pipeline == 1
        # multi-process pods keep the GSPMD path: its collectives and
        # the checkpoint gather are proven cross-host (test_multihost_*);
        # the shard_map overlap path is validated single-process so far
        and jax.process_count() == 1
    )


def _bucket_sync(axis: str):
    """Identity-forward / pmean-backward pytree transform. Each CALL is
    one gradient bucket: autodiff attaches the pmean where the call
    sits in the forward, so in the backward it fires as soon as every
    cotangent in that bucket exists."""

    @jax.custom_vjp
    def sync(tree):
        return tree

    def fwd(tree):
        return tree, None

    def bwd(_, ct):
        return (
            jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, axis), ct),
        )

    sync.defvjp(fwd, bwd)
    return sync


def make_param_sync(axis: str, bucket_layers: int):
    """``params -> params`` with one :func:`_bucket_sync` application per
    gradient bucket: the embedding table(s), every ``bucket_layers``
    consecutive transformer blocks, and the ln_f/lm_head tail. Backward
    runs tail -> blocks(L..1) -> embeddings, so the per-bucket pmeans
    stream in that order, each overlapping the remaining backward."""
    sync = _bucket_sync(axis)
    group = max(1, int(bucket_layers))

    def param_sync(params: dict) -> dict:
        blocks = params["blocks"]
        tail_keys = [k for k in ("ln_f", "lm_head") if k in params]
        embed_keys = [
            k for k in params if k != "blocks" and k not in tail_keys
        ]
        embed = sync({k: params[k] for k in embed_keys})
        tail = sync({k: params[k] for k in tail_keys})
        new_blocks = []
        for start in range(0, len(blocks), group):
            new_blocks.extend(sync(list(blocks[start:start + group])))
        return {**embed, **tail, "blocks": new_blocks}

    return param_sync


def _make_overlap_train_step(cfg: TrainConfig, mesh: Mesh):
    axis = "data"
    inner = make_step_fn(
        cfg,
        # mesh=None on purpose: inside shard_map every shard is a
        # single-device program — attention must take the plain
        # single-device dispatch, not the shard_map/ring wrappers
        mesh=None,
        param_sync=make_param_sync(axis, cfg.dp_bucket_layers),
        loss_sync=lambda l: jax.lax.pmean(l, axis),
        # grad_acc_steps > 1 syncs the ACCUMULATED grads once after the
        # microbatch scan instead of firing the bucketed pmeans inside
        # every microbatch's backward — with accumulation there is no
        # remaining backward to overlap after the scan anyway, and the
        # per-microbatch schedule moves A x the collective volume for a
        # numerically identical mean (train/step.py docstring)
        grad_sync=lambda g: jax.tree_util.tree_map(
            lambda t: jax.lax.pmean(t, axis), g
        ),
    )

    def raw(state, batch, rng=None):
        if rng is not None:
            # the dropout key rides in replicated (P() spec): fold the
            # shard index in so each data shard draws INDEPENDENT masks
            # for its slice of the batch, matching GSPMD semantics where
            # one global mask is sharded over the batch axis — without
            # this every shard reuses the same masks on its local
            # examples (correlated regularization across the data axis)
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))
        return inner(state, batch, rng)
    batch_specs = {
        # (A, B, T): microbatch axis replicated, batch sharded over data
        "x": P(None, axis, None),
        "y": P(None, axis, None),
    }
    if faults.nan_armed():
        # (A,) poison scales ride replicated, exactly like the GSPMD
        # path — armed faults never change the jit signature mid-run
        batch_specs["poison"] = P()

    sharded = shard_map(
        raw,
        mesh=mesh,
        in_specs=(P(), batch_specs, P()),
        out_specs=(P(), P()),
        # the custom-vjp pmean confuses the replication checker on some
        # jax versions; replication here is by construction (params and
        # synced grads are identical on every shard)
        check_vma=False,
    )
    # Explicit in/out shardings pin the steady state to ONE cache entry:
    # without them the first call sees the init-time state sharding
    # (state_sharding's size-1-axis specs) while every later call sees
    # the output's replicated sharding — a silent retrace on step 2, the
    # exact pathology the zero-recompile pin forbids. The one-time
    # reshard of the init state is free (size-1 mesh axes ARE
    # replication; no bytes move).
    repl = NamedSharding(mesh, P())
    jitted = jax.jit(
        sharded,
        in_shardings=(
            repl,
            {k: NamedSharding(mesh, s) for k, s in batch_specs.items()},
            None,
        ),
        out_shardings=(repl, None),
        donate_argnums=(0,),
    )

    def step(state: dict, batch: dict, rng=None):
        # normalize the state onto the replicated sharding BEFORE the
        # call: an init-time or resume-time state carries
        # state_sharding's size-1-axis specs, which are physically
        # identical to P() but a DIFFERENT jit cache key — without this
        # the first post-init step silently adds a second cache entry
        # (the compile-event pin watches exactly that). device_put
        # short-circuits when the sharding already matches, so steady
        # state pays one cheap equality sweep, no transfer.
        state = jax.device_put(state, repl)
        return jitted(state, batch, rng)

    return _attach_compile_counter(
        step, jitted, f"overlap-dp step (data={cfg.mesh.data}, "
        f"bucket={cfg.dp_bucket_layers} layers)"
    )


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def make_sharded_train_step(cfg: TrainConfig, mesh: Mesh, state_template: dict):
    """Returns ``step(state, batch, rng) -> (state, metrics)`` compiled
    with the mesh's shardings. ``state_template`` (abstract or concrete)
    supplies the pytree structure for sharding inference. Pure-DP meshes
    take the overlap-scheduled shard_map path (module docstring) unless
    ``cfg.dp_overlap`` is off."""
    if overlap_eligible(cfg):
        return _make_overlap_train_step(cfg, mesh)
    # attention_impl='pallas' on a >1-device mesh routes through the
    # shard_map wrapper (parallel/shard_flash.py) — batch on data/fsdp,
    # heads on tensor — or the ring path when sequence > 1. GSPMD never
    # sees a bare pallas_call.
    st_sh = state_sharding(state_template, mesh)
    b_sh = batch_sharding(mesh)
    batch_shardings = {"x": b_sh, "y": b_sh}
    if faults.nan_armed():
        # fault-injection poison scales ride replicated next to the batch
        # (chaos tests only; absent in production, so the jit signature —
        # and the compiled program — is unchanged when disarmed)
        batch_shardings["poison"] = NamedSharding(mesh, P())

    jitted = jax.jit(
        make_step_fn(cfg, mesh=mesh),
        in_shardings=(st_sh, batch_shardings, None),
        out_shardings=(st_sh, None),
        donate_argnums=(0,),
    )

    def step(state: dict, batch: dict, rng=None):
        return jitted(state, batch, rng)

    # surface the compile-event counter through the wrapper so the
    # trainer's obs layer works on sharded runs too (jit-cache entries
    # when the private attribute exists, backend-compile monitoring
    # otherwise — see _attach_compile_counter)
    return _attach_compile_counter(step, jitted, "gspmd step")


def create_sharded_train_state(key: jax.Array, cfg: TrainConfig, mesh: Mesh) -> dict:
    """Initialize the train state directly onto the mesh: the init is
    jitted with the state sharding as out_shardings, so each device
    materializes only its own shards (no host-side full copy)."""
    abstract = jax.eval_shape(lambda k: create_train_state(k, cfg), key)
    sh = state_sharding(abstract, mesh)
    init = jax.jit(lambda k: create_train_state(k, cfg), out_shardings=sh)
    return init(key)
