"""The sharded (multi-chip) training step.

``jax.jit`` with explicit in/out shardings over a Mesh: the partitioner
inserts the gradient psum over the ``data``/``fsdp`` axes and the
tensor-parallel all-gathers/reduce-scatters implied by the param specs —
this is the working replacement for the reference's imported-but-never-
used DDP/NCCL stack (train.py:7-10, 88).

The step body is IDENTICAL to the single-device one (train/step.py); only
the placement differs. That is the point of the SPMD design: one program,
any mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from differential_transformer_replication_tpu.config import TrainConfig
from differential_transformer_replication_tpu.parallel.sharding import (
    batch_sharding,
    state_sharding,
)
from differential_transformer_replication_tpu.train.step import (
    create_train_state,
    make_step_fn,
)
from differential_transformer_replication_tpu.utils import faults


def make_sharded_train_step(cfg: TrainConfig, mesh: Mesh, state_template: dict):
    """Returns ``step(state, batch, rng) -> (state, metrics)`` compiled with
    the mesh's shardings. ``state_template`` (abstract or concrete) supplies
    the pytree structure for sharding inference."""
    # attention_impl='pallas' on a >1-device mesh routes through the
    # shard_map wrapper (parallel/shard_flash.py) — batch on data/fsdp,
    # heads on tensor — or the ring path when sequence > 1. GSPMD never
    # sees a bare pallas_call.
    st_sh = state_sharding(state_template, mesh)
    b_sh = batch_sharding(mesh)
    batch_shardings = {"x": b_sh, "y": b_sh}
    if faults.nan_armed():
        # fault-injection poison scales ride replicated next to the batch
        # (chaos tests only; absent in production, so the jit signature —
        # and the compiled program — is unchanged when disarmed)
        batch_shardings["poison"] = NamedSharding(mesh, P())

    jitted = jax.jit(
        make_step_fn(cfg, mesh=mesh),
        in_shardings=(st_sh, batch_shardings, None),
        out_shardings=(st_sh, None),
        donate_argnums=(0,),
    )

    def step(state: dict, batch: dict, rng=None):
        return jitted(state, batch, rng)

    # surface the jit cache size through the wrapper so the trainer's
    # compile-event counter (obs layer) works on sharded runs too;
    # _cache_size is a private jit attribute — absent on some jax
    # versions, and a missing METRIC must never break training setup
    cache_size = getattr(jitted, "_cache_size", None)
    if cache_size is not None:
        step._cache_size = cache_size
    return step


def create_sharded_train_state(key: jax.Array, cfg: TrainConfig, mesh: Mesh) -> dict:
    """Initialize the train state directly onto the mesh: the init is
    jitted with the state sharding as out_shardings, so each device
    materializes only its own shards (no host-side full copy)."""
    abstract = jax.eval_shape(lambda k: create_train_state(k, cfg), key)
    sh = state_sharding(abstract, mesh)
    init = jax.jit(lambda k: create_train_state(k, cfg), out_shardings=sh)
    return init(key)
