"""Sharding rules: parameter, optimizer-state, and batch PartitionSpecs.

Path-based rules over the model's param pytree (the structures built in
``models/{control,diff,ndiff}.py``). The layout follows the standard
Megatron-style recipe mapped to this architecture:

  - Q/K/V projections shard the HEAD axis on ``tensor`` (column parallel);
    the merged-head einsum then runs on local heads only,
  - attention out-proj and FFN down-proj shard their INPUT dim on
    ``tensor`` (row parallel) — XLA inserts the psum,
  - FFN up-projections (SwiGLU gate/xform) shard the hidden dim,
  - token/position embeddings shard the vocab/position dim; lm_head
    shards vocab (logits stay vocab-sharded through the loss — XLA
    handles the sharded log-softmax),
  - GroupLayerNorm scale/bias shard with the head concat; block LayerNorm
    params replicate,
  - lambda vectors shard the head axis,
  - everything additionally shards its largest remaining dim over
    ``fsdp`` (ZeRO-style parameter sharding),
  - the batch shards over ``data`` (gradient psum over ``data`` is
    inserted by the partitioner — the DDP+NCCL equivalent the reference
    never wired up, train.py:7-10).

Optimizer state (AdamW mu/nu) inherits the param specs leafwise.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from differential_transformer_replication_tpu.config import ModelConfig


def spec_for(path: tuple, leaf: Any) -> P:
    """PartitionSpec for one param leaf, keyed on its path in the model
    pytree. ``path`` elements are jax DictKey/SequenceKey entries."""
    names = [
        k.key if hasattr(k, "key") else str(getattr(k, "idx", k)) for k in path
    ]
    name = names[-1]
    parent = names[-2] if len(names) >= 2 else ""
    rank = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))

    # embeddings: (V, E) / (S, E) -> shard rows on tensor, cols on fsdp
    if name in ("tok_emb", "pos_emb"):
        return P("tensor", "fsdp")
    if name in ("wq", "wk"):
        # (E, H, d) or (streams/terms, E, H, d): head axis on tensor
        if rank == 3:
            return P("fsdp", "tensor", None)
        return P(None, "fsdp", "tensor", None)
    if name == "wv":
        return P("fsdp", "tensor", None)  # (E, H, v)
    if name in ("lambda_q", "lambda_k"):
        return P(None, "tensor", None)  # (streams, H, d)
    if parent == "gn":
        return P("tensor")  # (H * 2d,) aligned with the head concat
    if parent == "out" and "attn" in names:
        # attention out-proj: (H*v, E) row parallel
        return P("tensor", "fsdp") if rank == 2 else P(None)
    if parent in ("gate", "xform"):
        # SwiGLU up-proj: (E, 4E) column parallel
        return P("fsdp", "tensor") if rank == 2 else P("tensor")
    if parent == "out" and "ffn" in names:
        # FFN down-proj: (4E, E) row parallel
        return P("tensor", "fsdp") if rank == 2 else P(None)
    if parent == "lm_head":
        # (E, V) vocab column parallel
        return P("fsdp", "tensor") if rank == 2 else P("tensor")
    # layer norms, scalars, anything else: replicated
    return P()


def make_param_specs(params: dict) -> dict:
    """A PartitionSpec pytree mirroring ``params``."""
    return jax.tree_util.tree_map_with_path(spec_for, params)


def state_sharding(state: dict, mesh: Mesh) -> dict:
    """NamedSharding pytree for the full train state.

    Works on the WHOLE state with the same path rules: optax's AdamW
    moments (mu/nu) mirror the param tree, so their leaf paths END with
    the same names (…/mu/blocks/0/attn/wq) and pick up the param's spec;
    scalars (count, step) fall through to replicated.
    """
    specs = jax.tree_util.tree_map_with_path(spec_for, state)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """(A, B, T) microbatched batch: shard the batch dim over data (+fsdp,
    which acts as a second data axis for the forward/backward) and the
    sequence dim over ``sequence`` (context parallelism — each device
    holds a T/P slice; attention rings over it, parallel/ring.py)."""
    return NamedSharding(mesh, P(None, ("data", "fsdp"), "sequence"))


def shard_state(state: dict, mesh: Mesh) -> dict:
    """Place an (unsharded) train state onto the mesh."""
    sh = state_sharding(state, mesh)
    return jax.tree_util.tree_map(jax.device_put, state, sh)
