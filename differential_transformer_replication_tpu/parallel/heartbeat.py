"""Multi-host liveness mesh: convert a dead peer into a fast restart.

When one host of a pod dies (SIGKILL preemption, kernel panic, network
partition), the surviving hosts do not crash — they block **forever**
inside the next collective, because the coordination layer only tears
the job down on the *coordinator's* timeout, which defaults to
minutes-to-never depending on the failure. This module is the
out-of-band liveness channel that the collectives lack:

- every process runs a **publisher** thread that writes a small
  ``(process_index, iter, seq, ts)`` heartbeat record every
  ``interval_s`` seconds, *off the train loop* (a wedged loop keeps
  beating; only a dead process goes silent — the local wedge case is
  the step-deadline watchdog's job, train/watchdog.py),
- every process runs a **monitor** thread that reads the peers'
  records and tracks, per peer, the local receipt time of the last
  *change* (``seq`` moved). Staleness is judged against the local
  monotonic clock — never against the peer's embedded wall-clock
  timestamp — so cross-host clock skew cannot fake a death,
- a peer silent past ``timeout_s`` triggers ``on_dead`` exactly once
  per peer — the trainer wires this to
  ``StepWatchdog.trip`` (coordinated abort): every surviving host
  dumps its hang report and exits with the ``hang`` code, the
  supervisor relaunches, and ``--resume-from auto`` (plus
  ``--elastic``) picks the run back up. An infinite wedge becomes a
  supervised restart within seconds.

Transport is pluggable and stdlib-only. :class:`FileHeartbeatTransport`
is the production default — one ``hb-<index>.json`` per process in a
shared-filesystem directory (pods already share checkpoint storage;
writes are atomic-rename so readers never see torn JSON).
:class:`MemoryTransport` backs the tier-1 tests: fake peers, fake
clock, no filesystem, no sleeping.

Observability: per-peer ``train_heartbeat_age_seconds{peer=...}``
gauges (pass the registry gauge in) and the watchdog's ``hang``
records carry the peer ages at abort time.

Fault points (utils/faults.py, resolved lazily so this module stays
importable without the package): ``heartbeat_silence@P`` mutes process
P's publisher — the alive-but-partitioned host.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Dict, Optional


def _faults():
    """utils/faults.py, resolved lazily (ckpt_writer.py convention):
    None when unavailable -> injection inert."""
    mod = sys.modules.get(
        "differential_transformer_replication_tpu.utils.faults"
    )
    if mod is not None:
        return mod
    try:
        from differential_transformer_replication_tpu.utils import faults
        return faults
    except Exception:  # standalone import without the package
        return None


class MemoryTransport:
    """In-process transport for tests: a dict guarded by a lock.
    ``publish`` upserts by process index; ``read`` snapshots. Tests
    plant fake-peer records directly via :meth:`publish`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: Dict[int, dict] = {}

    def publish(self, record: dict) -> None:
        with self._lock:
            self._records[int(record["process_index"])] = dict(record)

    def read(self) -> Dict[int, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._records.items()}


class FileHeartbeatTransport:
    """One ``hb-<index>.json`` per process in a shared directory.

    Writes go temp-file-then-rename so a reader never parses a torn
    record; a record that still fails to parse (foreign file, torn
    rename on an exotic filesystem) is skipped — a garbage file must
    degrade to "no data for that peer", never crash the monitor. No
    fsync: heartbeats are ephemeral liveness signals, not durable
    state, and an fsync per beat would hammer shared storage."""

    def __init__(self, directory: str) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, index: int) -> str:
        return os.path.join(self.directory, f"hb-{int(index)}.json")

    def publish(self, record: dict) -> None:
        path = self._path(record["process_index"])
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, path)
        except OSError:
            # a full/unreachable shared mount: this beat is lost; the
            # publisher retries next interval. Peers see a growing age
            # — which is the correct signal for "this host cannot
            # reach shared storage" anyway.
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def read(self) -> Dict[int, dict]:
        out: Dict[int, dict] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            if not name.startswith("hb-") or not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.directory, name)) as f:
                    rec = json.load(f)
                out[int(rec["process_index"])] = rec
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return out


class Heartbeat:
    """Publisher + monitor pair over a transport.

    ``iter_supplier`` returns the host-side iteration counter (read
    without locking — a torn read of an int is harmless telemetry
    noise). ``on_dead(peer_index, age_s)`` fires at most once per peer
    from the monitor thread. ``age_gauge`` is a labeled registry gauge
    (``labelnames=("peer",)``) or None.

    The two threads pace on ``Event.wait(timeout)`` — never a sleep
    under a lock — and both stop on :meth:`close`. With
    ``num_processes == 1`` the monitor has no peers and only the
    publisher runs (its record is still useful: an operator can watch
    a single-host run's liveness file).
    """

    def __init__(
        self,
        transport,
        process_index: int,
        num_processes: int,
        interval_s: float,
        timeout_s: float,
        iter_supplier: Callable[[], int],
        on_dead: Optional[Callable[[int, float], None]] = None,
        age_gauge=None,
        clock: Callable[[], float] = time.monotonic,
        start: bool = True,
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        if timeout_s <= interval_s:
            raise ValueError(
                f"timeout_s ({timeout_s}) must exceed interval_s "
                f"({interval_s}) — a timeout under one publish period "
                "declares every healthy peer dead"
            )
        self.transport = transport
        self.process_index = int(process_index)
        self.num_processes = int(num_processes)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._iter_supplier = iter_supplier
        self._on_dead = on_dead
        self._age_gauge = age_gauge
        self._clock = clock
        self._seq = 0
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # peer -> (last seq seen, local clock at last change); peers
        # get a full timeout of grace from monitor start, so a slow
        # peer bring-up (compiling) is not an instant death sentence
        now = clock()
        self._last_change: Dict[int, tuple] = {
            p: (None, now)
            for p in range(self.num_processes) if p != self.process_index
        }
        self._dead: set = set()
        self._threads = []
        if start:
            self.start()

    def start(self) -> None:
        self.publish_once()  # announce immediately (peers' grace clock)
        self._threads = [
            threading.Thread(target=self._publish_loop,
                             name="heartbeat-publish", daemon=True),
        ]
        if self._last_change:
            self._threads.append(threading.Thread(
                target=self._monitor_loop, name="heartbeat-monitor",
                daemon=True,
            ))
        for t in self._threads:
            t.start()

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)

    # -- publisher ------------------------------------------------------

    def publish_once(self) -> None:
        f = _faults()
        if f is not None and hasattr(f, "heartbeat_silenced") \
                and f.heartbeat_silenced(self.process_index):
            return  # chaos: this host is alive but unreachable
        self._seq += 1
        self.transport.publish({
            "process_index": self.process_index,
            "iter": int(self._iter_supplier()),
            "seq": self._seq,
            "ts": round(time.time(), 3),
        })

    def _publish_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.publish_once()

    # -- monitor --------------------------------------------------------

    def peer_ages(self) -> Dict[int, float]:
        """Seconds since each peer's record last changed, judged by the
        LOCAL clock (clock-skew immune)."""
        now = self._clock()
        with self._lock:
            return {
                p: now - seen for p, (_, seen) in self._last_change.items()
            }

    def check_peers(self) -> Dict[int, float]:
        """One monitor pass: refresh change times from the transport,
        export ages, fire ``on_dead`` for newly silent peers. Returns
        the age map (tests drive this synchronously with a fake
        clock)."""
        records = self.transport.read()
        now = self._clock()
        newly_dead = []
        with self._lock:
            for p in list(self._last_change):
                rec = records.get(p)
                last_seq, seen = self._last_change[p]
                if rec is not None and rec.get("seq") != last_seq:
                    self._last_change[p] = (rec.get("seq"), now)
                    continue
                if now - seen > self.timeout_s and p not in self._dead:
                    self._dead.add(p)
                    newly_dead.append((p, now - seen))
            ages = {
                p: now - seen for p, (_, seen) in self._last_change.items()
            }
        # gauge + callback OUTSIDE the lock: on_dead trips the
        # watchdog, which dumps reports and exits — never under a lock
        if self._age_gauge is not None:
            for p, age in ages.items():
                try:
                    self._age_gauge.set(age, peer=str(p))
                except Exception:  # noqa: BLE001
                    pass
        if self._on_dead is not None:
            for p, age in newly_dead:
                self._on_dead(p, age)
        return ages

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.check_peers()
