from differential_transformer_replication_tpu.parallel.mesh import create_mesh
from differential_transformer_replication_tpu.parallel.sharding import (
    batch_sharding,
    make_param_specs,
    shard_state,
    state_sharding,
)
from differential_transformer_replication_tpu.parallel.dp_step import (
    make_sharded_train_step,
)
from differential_transformer_replication_tpu.parallel.pipeline import (
    create_pipeline_train_state,
    make_pipeline_eval_many,
    make_pipeline_eval_step,
    make_pipeline_train_step,
)
from differential_transformer_replication_tpu.parallel.shard_flash import (
    shard_flash_diff_attention,
    shard_flash_multi_stream_attention,
    shard_flash_ndiff_attention,
    shard_flash_vanilla_attention,
)
from differential_transformer_replication_tpu.parallel.ulysses import (
    ulysses_multi_stream_attention,
)

__all__ = [
    "create_mesh",
    "make_param_specs",
    "batch_sharding",
    "state_sharding",
    "shard_state",
    "make_sharded_train_step",
    "create_pipeline_train_state",
    "make_pipeline_eval_many",
    "make_pipeline_eval_step",
    "make_pipeline_train_step",
    "shard_flash_multi_stream_attention",
    "shard_flash_vanilla_attention",
    "shard_flash_diff_attention",
    "shard_flash_ndiff_attention",
    "ulysses_multi_stream_attention",
]
