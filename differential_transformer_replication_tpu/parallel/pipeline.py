"""Pipeline parallelism: GPipe microbatch scheduling over the mesh's
``pipeline`` axis.

The reference has no pipeline (or any working distributed) machinery —
its DDP/NCCL imports are dormant (train.py:7-10, 88; SURVEY.md section
2.3). This module is the TPU-native scale-out lever the reference never
built: transformer layers are split into P contiguous stages, one per
device along the ``pipeline`` mesh axis, and microbatches stream through
the stages with activations handed to the next stage by
``jax.lax.ppermute``. The pipeline axis is the LAST, stride-1 mesh axis
(config.py) so neighboring stages are adjacent in ``jax.devices()``
enumeration order — a good default for the handoff, though physical
torus adjacency on large slices is the device-assignment problem
``mesh_utils.create_device_mesh`` exists for.

Design (the standard SPMD pipelining recipe, cf. the public JAX scaling
playbook):

  - **Stage-stacked parameters.** The per-layer ``blocks`` list is
    stacked on a leading layer axis and sharded ``P('pipeline')``: each
    device holds ``n_layer / P`` consecutive layers and scans over them
    (``lax.scan``), with the TRACED 1-based layer index
    ``stage * Lp + j + 1`` feeding the dynamic lambda-init schedule
    (ops/lambdas.py handles traced indices).
  - **GPipe schedule.** With M microbatches (the ``grad_acc_steps`` axis
    of the batch — pipeline microbatching IS gradient accumulation) the
    loop runs ``M + P - 1`` ticks. At tick t, stage s computes microbatch
    ``t - s``; stage 0 feeds ``h0[t]``; the last stage collects outputs
    for microbatch ``t - (P-1)``. Every stage computes every tick (the
    classic ``(P-1)/(M+P-1)`` bubble is idle-compute on garbage, masked
    out of the loss), so keep ``M >= P`` for efficiency.
  - **Embed / head placement.** Embedding and lm-head params are
    replicated over the pipeline axis; each stage computes the (cheap)
    embedding of its own feeds, and only the LAST stage's head output
    enters the loss (``where``-masked, then ``psum`` broadcasts the loss
    so the shard_map output is replicated).
  - **Autodiff does 1F1B's work.** ``jax.grad`` through the tick scan
    transposes each ``ppermute`` into the reverse rotation: the backward
    pass is automatically the mirrored pipeline, and cotangents for the
    replicated embed/head params are psummed across the mesh by
    shard_map's transpose.

Composition: the ``data`` (and ``fsdp``, treated as a second data axis)
mesh dims shard the microbatch batch dim — grads are averaged across
them inside the loss (``pmean``), so one shard_mapped function delivers
PP x DP. The ``tensor`` axis composes too, via shard_map's manual/auto
split: the schedule is MANUAL over ``data``/``fsdp``/``pipeline`` only
(``axis_names``), leaving ``tensor`` an AUTO axis that GSPMD partitions
inside each stage with the Megatron specs from parallel/sharding.py
(Q/K/V head-column, out-proj/down-proj row + psum, vocab-sharded
embedding and lm-head loss). One caveat, documented not hidden: a
``pallas_call`` cannot be GSPMD-partitioned, so under pipeline x tensor
the fused attention kernel's operands are gathered per tensor shard and
the kernel runs replicated over ``tensor`` — the MXU-heavy projections,
FFN, and lm-head still shard. Use ``attention_impl='xla'`` when tensor
sharding of the attention math itself matters under pipeline.

``sequence`` composes the same way, as a second AUTO axis: activations
and the token batch shard their T dim, so LN/FFN/projections/loss are
sequence-parallel and GSPMD inserts the K/V all-gather inside dense
attention (the Megatron-SP flavor of context parallelism — NOT the ring
schedule, which owns its own manual shard_map over ``sequence`` on the
GSPMD path, parallel/ring.py, and cannot nest inside this one).

Restrictions (checked): ``n_layer % P == 0`` and — at train-step
construction — ``micro_batch_size`` divisible by data*fsdp. Dropout is
supported: the step's rng is folded per (data-shard, microbatch, layer)
through the tick schedule (make_pipeline_loss).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.models import common, model_module
from differential_transformer_replication_tpu.ops import causal_mask, rope_cos_sin
from differential_transformer_replication_tpu.parallel.sharding import spec_for
from differential_transformer_replication_tpu.train.optim import make_optimizer
from differential_transformer_replication_tpu.train.step import create_train_state
from differential_transformer_replication_tpu.utils.compat import shard_map as _shard_map

_DATA_AXES = ("data", "fsdp")
_PIPE_AXIS = "pipeline"


# ---------------------------------------------------------------------------
# Param layout: list-of-blocks <-> stage-stacked


def stack_blocks(params: dict) -> dict:
    """Model params with the per-layer ``blocks`` list stacked on a leading
    layer axis (so it can shard ``P('pipeline')``). All other entries
    (embeddings, final norm, lm head) pass through unchanged."""
    out = dict(params)
    out["blocks"] = common.stack_block_list(params["blocks"])
    return out


def unstack_blocks(params: dict, n_layer: int) -> dict:
    """Inverse of :func:`stack_blocks` — back to the list layout the
    single-device/GSPMD paths and ``save_pretrained`` use."""
    out = dict(params)
    out["blocks"] = common.unstack_block_tree(params["blocks"], n_layer)
    return out


def _path_names(path) -> list:
    return [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]


class _Rank:
    """Stand-in leaf for sharding.spec_for with the stacked leading
    layer axis stripped off."""

    def __init__(self, ndim: int):
        self.ndim = ndim


def _drop_fsdp(spec: P) -> tuple:
    """Under pipeline the fsdp mesh dim is a second DATA axis (params
    replicate over it, see the warning in _check_pipeline_cfg), so strip
    it from the GSPMD base spec."""
    return tuple(None if s == "fsdp" else s for s in spec)


def _pipe_spec(path, leaf) -> P:
    """Stacked block leaves shard their leading (layer) axis over
    ``pipeline`` and their remaining dims with the Megatron ``tensor``
    rules (parallel/sharding.py, minus fsdp — see _drop_fsdp); embed/head
    params take the same tensor rules without the layer axis; optimizer
    scalars replicate. Optimizer moments mirror the param tree so their
    paths also contain ``blocks`` and inherit the combined sharding."""
    rank = getattr(leaf, "ndim", 0)
    if "blocks" in _path_names(path) and rank >= 1:
        base = _drop_fsdp(spec_for(path, _Rank(rank - 1)))
        return P(_PIPE_AXIS, *base)
    return P(*_drop_fsdp(spec_for(path, leaf)))


def pipeline_state_sharding(state, mesh: Mesh):
    """NamedSharding pytree for a stage-stacked train state."""
    specs = jax.tree_util.tree_map_with_path(_pipe_spec, state)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


# ---------------------------------------------------------------------------
# The pipelined loss


def _check_pipeline_cfg(model_cfg: ModelConfig, mesh: Mesh) -> int:
    n_stages = mesh.shape.get(_PIPE_AXIS, 1)
    if n_stages < 2:
        raise ValueError(f"pipeline axis must be > 1, got mesh {dict(mesh.shape)}")
    auto_sharded = [
        ax for ax in ("tensor", "sequence") if mesh.shape.get(ax, 1) != 1
    ]
    if auto_sharded and model_cfg.attention_impl == "pallas":
        import warnings

        warnings.warn(
            f"pipeline x {'/'.join(auto_sharded)} with attention_impl="
            "'pallas': GSPMD cannot partition the fused attention kernel, "
            "so its operands are gathered and the kernel runs REPLICATED "
            f"over the {'/'.join(auto_sharded)} axis "
            "(projections/FFN/lm-head still shard). Use attention_impl="
            "'xla' if attention-math sharding matters here",
            stacklevel=3,
        )
    if mesh.shape.get("sequence", 1) != 1:
        import warnings

        warnings.warn(
            "under pipeline parallelism the sequence axis is GSPMD-SP only: "
            f"sequence_impl={model_cfg.sequence_impl!r} (the ring / ulysses "
            "schedules own their own shard_map and cannot nest inside the "
            "pipeline's) is IGNORED here — activations shard their T dim and "
            "GSPMD inserts the K/V all-gather inside dense attention instead. "
            "Drop --pipeline-parallel if the ring/ulysses schedule itself "
            "matters",
            stacklevel=3,
        )
    if mesh.shape.get("fsdp", 1) != 1:
        import warnings

        warnings.warn(
            "under pipeline parallelism the fsdp axis acts as a SECOND DATA "
            "axis only: non-block params and all optimizer state are "
            "replicated, not ZeRO-sharded (parallel/pipeline.py:_pipe_spec). "
            "Use the GSPMD path (no --pipeline-parallel) for real parameter "
            "sharding",
            stacklevel=3,
        )
    if model_cfg.n_layer % n_stages:
        raise ValueError(
            f"n_layer={model_cfg.n_layer} not divisible by pipeline={n_stages}"
        )
    return n_stages


def make_pipeline_loss(model_cfg: ModelConfig, mesh: Mesh):
    """Returns ``loss(params_stacked, x, y, rng=None) -> scalar`` where
    ``x``/``y`` are ``(M, B, T)`` microbatched token/target ids. The
    scalar is the microbatch-mean loss, averaged over data shards —
    identical semantics to the grad-accumulation scan in train/step.py.

    With ``rng`` given and ``model_cfg.dropout > 0``, dropout is live:
    each (data-shard, microbatch, layer) gets an independent key — the
    base key is folded with the shard's mesh position, then with the
    microbatch index inside the tick, and block_forward splits per
    layer. Without a key, dropout is inert (eval semantics)."""
    n_stages = _check_pipeline_cfg(model_cfg, mesh)
    if model_cfg.ffn_impl != "xla":
        # the stage body is a per-device program (shard_map), so a bare
        # pallas_call would be legal here — but the fused FFN/norm
        # kernels are validated on the single-device and overlap-DP
        # paths only; keep pipeline placements on the reference XLA
        # composition, matching the documented use_fused_ffn fallback
        # for every other multi-device placement (models/common.py)
        model_cfg = model_cfg.replace(ffn_impl="xla")
    layers_per_stage = model_cfg.n_layer // n_stages
    mod = model_module(model_cfg)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def spmd(blocks_loc, rest, x, y, rng):
        # blocks_loc: stage's stacked layers (leading axis layers_per_stage)
        # rest: embed/ln_f/lm_head params, replicated; x/y: (M, B_loc, T)
        # rng: (2,) uint32 key or None (traced; replicated spec)
        stage = jax.lax.axis_index(_PIPE_AXIS)
        M, B, T = x.shape
        is_last = stage == n_stages - 1
        if rng is not None:
            # distinct masks per data shard (the batch is sharded, so the
            # same key on every shard would reuse masks across examples)
            pos = (
                jax.lax.axis_index(_DATA_AXES[0]) * mesh.shape[_DATA_AXES[1]]
                + jax.lax.axis_index(_DATA_AXES[1])
            )
            rng = jax.random.fold_in(rng, pos)

        cos, sin = (
            rope_cos_sin(model_cfg.head_size, T)
            if mod.USES_ROPE
            else (None, None)
        )
        mask = causal_mask(T)

        def stage_fn(h, mb_rng):
            def layer(h, xs):
                blk, j = xs
                li = stage * layers_per_stage + j + 1  # 1-based, traced
                r = None if mb_rng is None else jax.random.fold_in(mb_rng, li)
                fn = lambda h, blk: mod.block_forward(
                    h, blk, li, model_cfg, cos, sin, mask, r
                )
                if model_cfg.remat:
                    policy = common.resolve_remat_policy(
                        model_cfg.remat_policy
                    )
                    kw = {} if policy is None else {"policy": policy}
                    fn = jax.checkpoint(fn, **kw)
                return fn(h, blk), None

            h, _ = jax.lax.scan(
                layer, h, (blocks_loc, jnp.arange(layers_per_stage))
            )
            return h

        def tick(carry, t):
            state, loss_sum = carry
            # embed the fed microbatch lazily inside the tick (token-id
            # gather, cheap every tick) instead of prefetching all M
            # embedded microbatches — that buffer was (M, B, T, E), the
            # largest tensor in the schedule at long context
            feed = mod.embed(rest, x[jnp.clip(t, 0, M - 1)], model_cfg)
            inp = jnp.where(stage == 0, feed, state)
            # the microbatch this stage works on at tick t (clipped garbage
            # during bubble ticks — its output is never used)
            mb = jnp.clip(t - stage, 0, M - 1)
            mb_rng = None if rng is None else jax.random.fold_in(rng, mb)
            out = stage_fn(inp, mb_rng)
            o_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
            valid = jnp.logical_and(is_last, t - (n_stages - 1) >= 0)

            # Head + loss on the just-finished microbatch, INSIDE the tick:
            # the carry stays O(B*T*E) plus a scalar instead of collecting
            # all M outputs for a second scan — at long context the
            # (M, B, T, E) collection was the largest tensor in the
            # schedule. lax.cond skips the lm-head matmul entirely on
            # bubble ticks and on every non-last stage; tail_and_loss
            # honors cfg.loss_chunk (the fused chunked head, ops/losses.py)
            # here too.
            def head_loss(op):
                h, idx = op
                yi = jax.lax.dynamic_index_in_dim(y, idx, 0, keepdims=False)
                _, l = common.tail_and_loss(h, rest, model_cfg, yi)
                return l
            l = jax.lax.cond(
                valid, head_loss, lambda op: jnp.zeros(()), (out, o_idx)
            )
            state = jax.lax.ppermute(out, _PIPE_AXIS, perm)
            return (state, loss_sum + l), None

        E = rest["tok_emb"].shape[-1]
        compute = jnp.dtype(model_cfg.compute_dtype)
        (_, loss_sum), _ = jax.lax.scan(
            tick,
            (jnp.zeros((B, T, E), compute), jnp.zeros(())),
            jnp.arange(M + n_stages - 1),
        )
        loss_loc = jnp.where(is_last, loss_sum / M, 0.0)
        loss = jax.lax.psum(loss_loc, _PIPE_AXIS)  # broadcast to all stages
        return jax.lax.pmean(loss, _DATA_AXES)

    # MANUAL over the schedule axes only: ``tensor`` stays an AUTO axis,
    # so GSPMD partitions each stage's matmuls/loss with the Megatron
    # shardings the params carry (pipeline_state_sharding) — in_specs
    # describe the manual axes and the tensor sharding rides along on the
    # arguments themselves.
    manual_axes = frozenset({*_DATA_AXES, _PIPE_AXIS})
    data_specs = (P(_PIPE_AXIS), P(), P(None, _DATA_AXES, None),
                  P(None, _DATA_AXES, None))
    # jit is required, not decorative: shard_map's EAGER impl path
    # (_unmatch_spec, jax 0.9) rejects a manual-subset axis_names; under
    # jit the auto axes partition correctly. Nested under the train-step
    # jit this inlines.
    smapped_plain = jax.jit(_shard_map(
        lambda b, r, x, y: spmd(b, r, x, y, None),
        mesh=mesh,
        in_specs=data_specs,
        out_specs=P(),
        axis_names=manual_axes,
        check_vma=False,
    ))
    smapped_dropout = jax.jit(_shard_map(
        spmd,
        mesh=mesh,
        in_specs=data_specs + (P(),),
        out_specs=P(),
        axis_names=manual_axes,
        check_vma=False,
    ))

    def loss_fn(
        params: dict, x: jnp.ndarray, y: jnp.ndarray, rng=None
    ) -> jnp.ndarray:
        blocks = params["blocks"]
        rest = {k: v for k, v in params.items() if k != "blocks"}
        if rng is not None and model_cfg.dropout > 0.0:
            return smapped_dropout(blocks, rest, x, y, rng)
        return smapped_plain(blocks, rest, x, y)

    return loss_fn


# ---------------------------------------------------------------------------
# Train / eval steps


def create_pipeline_train_state(key: jax.Array, cfg: TrainConfig, mesh: Mesh) -> dict:
    """Train state in the stage-stacked layout, initialized directly onto
    the mesh (each stage materializes only its own layers)."""
    model_cfg = cfg.resolved_model()
    _check_pipeline_cfg(model_cfg, mesh)
    tx, _ = make_optimizer(cfg)

    def init(k):
        state = create_train_state(k, cfg)
        params = stack_blocks(state["params"])
        return {
            "params": params,
            "opt_state": tx.init(params),
            "step": jnp.zeros((), jnp.int32),
        }

    abstract = jax.eval_shape(init, key)
    sh = pipeline_state_sharding(abstract, mesh)
    return jax.jit(init, out_shardings=sh)(key)


def make_pipeline_train_step(cfg: TrainConfig, mesh: Mesh, state_template: dict):
    """``step(state, batch, rng=None) -> (state, metrics)`` — same contract
    and metrics as the GSPMD step (parallel/dp_step.py), compiled over the
    pipeline mesh. ``batch['x']``/``['y']`` are ``(A, B, T)``: the
    grad-accumulation axis doubles as the pipeline microbatch stream."""
    model_cfg = cfg.resolved_model()
    n_stages = _check_pipeline_cfg(model_cfg, mesh)
    data_shards = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    if cfg.micro_batch_size % data_shards:
        raise ValueError(
            f"micro_batch_size={cfg.micro_batch_size} not divisible by the "
            f"data*fsdp shard count {data_shards} (mesh {dict(mesh.shape)})"
        )
    if cfg.grad_acc_steps < n_stages:
        import warnings

        warnings.warn(
            f"grad_acc_steps={cfg.grad_acc_steps} < pipeline stages "
            f"{n_stages}: the GPipe bubble dominates; use at least "
            f"{n_stages} (ideally a few x) microbatches",
            stacklevel=2,
        )
    tx, schedule = make_optimizer(cfg)
    loss_f = make_pipeline_loss(model_cfg, mesh)

    def raw_step(state, batch, rng=None):
        loss, grads = jax.value_and_grad(loss_f)(
            state["params"], batch["x"], batch["y"], rng
        )
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        metrics = {
            "loss": loss,
            "learning_rate": schedule(state["step"]),
            "grad_norm": optax.global_norm(grads),
        }
        return new_state, metrics

    st_sh = pipeline_state_sharding(state_template, mesh)
    # T shards over the AUTO sequence axis (GSPMD-SP); the manual in_specs
    # only describe the data axes, the sequence sharding rides along
    b_sh = NamedSharding(mesh, P(None, _DATA_AXES, "sequence"))
    jitted = jax.jit(
        raw_step,
        in_shardings=(st_sh, {"x": b_sh, "y": b_sh}, None),
        out_shardings=(st_sh, None),
        donate_argnums=(0,),
    )

    def step(state: dict, batch: dict, rng=None):
        return jitted(state, batch, rng)

    return step


def make_pipeline_eval_step(cfg: TrainConfig, mesh: Mesh):
    """``eval_step(params, x, y) -> loss`` on stage-stacked params; ``x``
    is a single (B, T) batch, run through the pipeline as one microbatch
    (bubble fraction (P-1)/P — use :func:`make_pipeline_eval_many` for
    eval loops)."""
    model_cfg = cfg.resolved_model()
    loss_f = make_pipeline_loss(model_cfg, mesh)

    @jax.jit
    def eval_step(params: dict, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
        return loss_f(params, x[None], y[None])

    return eval_step


def make_pipeline_eval_many(cfg: TrainConfig, mesh: Mesh):
    """``eval_many(params, xs, ys) -> scalar mean loss`` over a stacked
    (K, B, T) eval set, fed through the pipeline as ONE K-microbatch
    stream: the GPipe bubble amortizes to (P-1)/(K+P-1) instead of
    (P-1)/P at every one of estimate_loss's eval_iters calls (VERDICT r1
    item 7). The scalar mean over the stream equals the mean of per-batch
    losses (equal batch sizes)."""
    model_cfg = cfg.resolved_model()
    loss_f = make_pipeline_loss(model_cfg, mesh)

    @jax.jit
    def eval_many(params: dict, xs: jnp.ndarray, ys: jnp.ndarray) -> jnp.ndarray:
        return loss_f(params, xs, ys)

    return eval_many
