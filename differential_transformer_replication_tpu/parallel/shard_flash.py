"""The fused flash kernel on multi-device GSPMD meshes.

GSPMD cannot partition a bare ``pallas_call`` — on a >1-device mesh the
partitioner would all-gather every attention operand around the kernel
(or fail to compile). But the kernel's grid is already per-(batch, head):
batch and head are embarrassingly parallel for causal attention with an
unsharded sequence. So the composition is a ``shard_map`` whose in_specs
put batch on ``data``/``fsdp`` and heads on ``tensor`` — each device runs
the ordinary single-device kernel (ops/flash.py) on its local
(B/dp, T, H/tp) slice, with zero collectives inside attention. The
custom VJP differentiates through shard_map unchanged (batch/head
splitting needs no transposed collectives).

This is the missing composition called out in VERDICT r1 item 2 — it
makes ``attention_impl='pallas'`` work on the north-star DP/TP mesh
configs (BASELINE.json configs 3/5) instead of raising. Sequence-
parallel meshes take the ring path instead (parallel/ring.py), which
also reaches the chunk kernel via its own shard_map.

Reference analog: none — the reference computes attention per-head in
Python loops on one device (diff_transformer.py:89); this module plus
ops/flash.py is its TPU-native replacement at mesh scale.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from differential_transformer_replication_tpu.ops.flash import (
    multi_stream_flash_attention,
)
from differential_transformer_replication_tpu.ops.streams import (
    diff_coeffs,
    ndiff_coeffs,
    vanilla_coeffs,
)
from differential_transformer_replication_tpu.utils.compat import shard_map as _shard_map

_BATCH_AXES = ("data", "fsdp")
_HEAD_AXIS = "tensor"


def use_shard_flash(mesh: Optional[Mesh]) -> bool:
    """The shard_map wrapper applies whenever a >1-device mesh is threaded
    into the forward (and attention is not on the ring path — callers
    check ``use_ring`` first)."""
    return mesh is not None and mesh.devices.size > 1


def shard_flash_multi_stream_attention(
    qs: jnp.ndarray,  # (S, B, T, H, d) global
    ks: jnp.ndarray,  # (S, B, T, H, d)
    v: jnp.ndarray,  # (B, T, H, dv)
    coeffs: jnp.ndarray,  # (S, H) float32
    mesh: Mesh,
    *,
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jnp.ndarray:
    """``multi_stream_flash_attention`` with batch sharded over
    data x fsdp and heads over tensor. Global shapes in, global out —
    callable from inside the outer GSPMD jit.

    With active dropout, the replicated rng key is folded with the
    device's mesh position inside the shard_map body: the kernel keys its
    masks on the LOCAL (b*H + h) grid index, which repeats across shards,
    so without the fold every batch/head shard would reuse the same
    masks."""
    qk_spec = P(None, _BATCH_AXES, None, _HEAD_AXIS, None)
    v_spec = P(_BATCH_AXES, None, _HEAD_AXIS, None)
    c_spec = P(None, _HEAD_AXIS)
    use_drop = dropout_rate > 0.0 and dropout_rng is not None

    if use_drop:
        def body(qs_l, ks_l, v_l, c_l, rng):
            pos = (
                jax.lax.axis_index(_BATCH_AXES[0]) * mesh.shape[_BATCH_AXES[1]]
                + jax.lax.axis_index(_BATCH_AXES[1])
            ) * mesh.shape[_HEAD_AXIS] + jax.lax.axis_index(_HEAD_AXIS)
            return multi_stream_flash_attention(
                qs_l, ks_l, v_l, c_l,
                dropout_rate=dropout_rate,
                dropout_rng=jax.random.fold_in(rng, pos),
            )

        inner = _shard_map(
            body,
            mesh=mesh,
            in_specs=(qk_spec, qk_spec, v_spec, c_spec, P()),
            out_specs=v_spec,
            check_vma=False,
        )
        return inner(qs, ks, v, coeffs, dropout_rng)

    def body(qs_l, ks_l, v_l, c_l):
        return multi_stream_flash_attention(qs_l, ks_l, v_l, c_l)

    inner = _shard_map(
        body,
        mesh=mesh,
        in_specs=(qk_spec, qk_spec, v_spec, c_spec),
        out_specs=v_spec,
        check_vma=False,
    )
    return inner(qs, ks, v, coeffs)


def shard_flash_vanilla_attention(q, k, v, mesh: Mesh, **kw):
    """Mesh form of ops.flash.flash_vanilla_attention."""
    return shard_flash_multi_stream_attention(
        q[None], k[None], v, vanilla_coeffs(q.shape[2]), mesh, **kw
    )


def shard_flash_diff_attention(q1, k1, q2, k2, v, lam, mesh: Mesh, **kw):
    """Mesh form of ops.flash.flash_diff_attention: coeffs [1, -lambda]
    (diff_transformer.py:70)."""
    qs = jnp.stack([q1, q2])
    ks = jnp.stack([k1, k2])
    return shard_flash_multi_stream_attention(
        qs, ks, v, diff_coeffs(lam), mesh, **kw
    )


def shard_flash_ndiff_attention(qs, ks, v, lams, signs, mesh: Mesh, **kw):
    """Mesh form of ops.flash.flash_ndiff_attention: coeffs
    ``sign_s * lambda_{s,h}`` (Ndiff_transformer.py:119-123)."""
    return shard_flash_multi_stream_attention(
        qs, ks, v, ndiff_coeffs(lams, signs), mesh, **kw
    )
