"""All-to-all (Ulysses-style) sequence parallelism — the second context-
parallel strategy beside the ring (parallel/ring.py).

The reference has no sequence parallelism of any kind (SURVEY.md
section 5.7: a hard block_size=512 with dense per-head maps). This
module implements the all-to-all recipe on XLA collectives: activations
arrive sharded on the SEQUENCE dim; one ``jax.lax.all_to_all`` over the
``sequence`` mesh axis re-shards attention's inputs from
(T/P, H-local) to (T-full, H-local/P) — every device then runs ordinary
FULL-sequence causal attention over its head slice, and a second
all-to-all restores the sequence sharding. Outside attention (LN, FFN,
projections, loss) everything stays sequence-sharded.

Trade-off vs the ring, honestly stated: the ring keeps per-device
attention memory at O(Tl) and overlaps K/V rotation with compute, but
its chunk schedule runs P sequential steps; all-to-all pays two
collectives and holds full-T K/V per device — in exchange the inner
attention is ONE call on contiguous data, so the fused Pallas kernel
(ops/flash.py) runs unmodified at full efficiency (the ring reaches the
kernel only in its offset-causal chunk form). Pick per workload with
``ModelConfig.sequence_impl`` ("ring" default | "ulysses").

Constraint (checked): local heads H/tensor must divide by the sequence
axis — each sequence shard takes an equal head group.

With dropout, the replicated key is folded with the device's full mesh
position (the shard_flash.py pattern): after the all-to-all each device
keys masks on LOCAL (b*h) indices, which repeat across shards, so the
fold is what keeps every batch/head shard's masks independent.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from differential_transformer_replication_tpu.ops.streams import NEG_INF
from differential_transformer_replication_tpu.parallel.ring import (
    sequence_shard_map,
)

_SEQ_AXIS = "sequence"


def _check_heads(n_head_local: int, p: int) -> int:
    if n_head_local % p:
        raise ValueError(
            f"ulysses sequence parallelism needs local heads divisible by "
            f"the sequence axis: {n_head_local} heads per tensor shard vs "
            f"sequence={p} (use the ring, sequence_impl='ring', for uneven "
            f"head counts)"
        )
    return n_head_local // p


def _dense_full_attention(qs, ks, v, coeffs, dropout_rate, rng):
    """Full-sequence multi-stream causal attention on local heads —
    the XLA body after the first all-to-all. qs/ks: (S, B, T, h, d),
    v: (B, T, h, dv), coeffs: (S, h). Softmax-then-dropout per map with
    inverted scaling (diff_transformer.py:58-67 semantics)."""
    S, B, T, h, d = qs.shape
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum(
        "sbthd,sbuhd->sbhtu", qs.astype(jnp.float32), ks.astype(jnp.float32)
    ) * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
    s = jnp.where((cols <= rows)[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0 and rng is not None:
        keep = jax.random.bernoulli(rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    out_s = jnp.einsum("sbhtu,buhd->sbthd", p, v.astype(jnp.float32))
    out = jnp.einsum("sbthd,sh->bthd", out_s, coeffs.astype(jnp.float32))
    return out.astype(v.dtype)


def ulysses_multi_stream_attention(
    qs: jnp.ndarray,  # (S, B, T, H, d) global, T sharded over sequence
    ks: jnp.ndarray,
    v: jnp.ndarray,  # (B, T, H, dv)
    coeffs: jnp.ndarray,  # (S, H) float32
    mesh: Mesh,
    impl: str = "xla",
    *,
    dropout_rate: float = 0.0,
    dropout_rng=None,
) -> jnp.ndarray:
    """Causal multi-stream attention, sequence-sharded via all-to-all.
    Global shapes in, global out — callable from inside an outer jit;
    composes with data/fsdp batch sharding and tensor head sharding.

    ``impl``: "pallas" runs the fused flash kernel on the re-sharded
    full-T head slice (the aligned-causal kernel, unmodified); "xla"
    computes the dense masked softmax."""
    p_seq = mesh.shape[_SEQ_AXIS]
    use_drop = dropout_rate > 0.0 and dropout_rng is not None

    def body(qs_l, ks_l, v_l, c_l, rng):
        # local shapes: (S, B, Tl, Hl, d) / (B, Tl, Hl, dv) / (S, Hl);
        # rng arrives already folded per mesh position
        # (ring.sequence_shard_map)
        hh = _check_heads(qs_l.shape[3], p_seq)
        # all-to-all #1: gather the sequence, split the heads — shard i
        # of the sequence axis takes head group i of this tensor shard
        q_g = jax.lax.all_to_all(
            qs_l, _SEQ_AXIS, split_axis=3, concat_axis=2, tiled=True
        )  # (S, B, T, Hl/P, d)
        k_g = jax.lax.all_to_all(
            ks_l, _SEQ_AXIS, split_axis=3, concat_axis=2, tiled=True
        )
        v_g = jax.lax.all_to_all(
            v_l, _SEQ_AXIS, split_axis=2, concat_axis=1, tiled=True
        )  # (B, T, Hl/P, dv)
        my = jax.lax.axis_index(_SEQ_AXIS)
        c_g = jax.lax.dynamic_slice_in_dim(c_l, my * hh, hh, axis=1)

        if impl == "pallas":
            from differential_transformer_replication_tpu.ops.flash import (
                multi_stream_flash_attention,
            )

            out_g = multi_stream_flash_attention(
                q_g, k_g, v_g, c_g,
                dropout_rate=dropout_rate, dropout_rng=rng,
            )
        else:
            out_g = _dense_full_attention(
                q_g, k_g, v_g, c_g, dropout_rate, rng
            )
        # all-to-all #2: back to sequence sharding, heads re-gathered
        return jax.lax.all_to_all(
            out_g, _SEQ_AXIS, split_axis=1, concat_axis=2, tiled=True
        )  # (B, Tl, Hl, dv)

    return sequence_shard_map(
        body, mesh, qs, ks, v, coeffs,
        dropout_rng=dropout_rng if use_drop else None,
    )
