"""Metric logging with pluggable sinks.

Replicates the reference's observability surface (train.py:286-304):
stdout prints in the same format, the same metric names and cadence
(``iter``/``loss``/``learning_rate``/``gpu_memory`` every log_interval;
``train_loss``/``val_loss`` every eval_interval), with sinks:
  - stdout (always),
  - JSONL append (replaces wandb as the durable record; always unless
    disabled),
  - wandb (optional, only if installed and enabled — the reference hard
    -requires it, train.py:15,151).

Beyond the reference surface:
  - every record carries ``ts`` (unix wall-clock seconds) so records
    are joinable across restarts and supervisor relaunches,
  - each logger writes a one-time ``run_header`` record (config hash,
    jax version, device kind, process count) identifying the process
    that produced the records that follow it — a resumed/relaunched run
    appends a new header, so ``tools/metrics_report.py`` can segment
    the stream by incarnation,
  - ``gpu_memory`` keeps the reference's key name for drop-in dashboard
    compatibility but reports the accelerator's allocated bytes in MB —
    and is OMITTED (not logged as a misleading 0.0) on platforms
    without memory stats (CPU),
  - :meth:`MetricLogger.log_record` appends arbitrary typed records
    (the obs/introspect.py lambda summaries ride this).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from typing import Optional

import jax

from differential_transformer_replication_tpu.config import TrainConfig


def device_memory_mb() -> Optional[float]:
    """Allocated device memory in MB (the reference logs
    torch.cuda.memory_allocated/1024**2, train.py:293), or None when the
    platform exposes no memory stats (CPU, some simulators) — callers
    must OMIT the metric rather than log a misleading zero."""
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats or "bytes_in_use" not in stats:
        return None
    return stats["bytes_in_use"] / 1024**2


def config_hash(cfg: TrainConfig) -> str:
    """Stable short hash of the full recipe — the run identity key in
    ``run_header`` records (two streams with the same hash are the same
    experiment, whatever host/restart produced them)."""
    blob = json.dumps(cfg.to_dict(), sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


class MetricLogger:
    def __init__(self, cfg: TrainConfig, run_config: Optional[dict] = None):
        self.cfg = cfg
        self._jsonl = None
        self._wandb = None
        # records arrive from the train loop AND from background
        # producers (the device-profile sampler's parse worker routes
        # its rows through log_record) — TextIOWrapper writes are not
        # thread-safe, and a torn mid-line interleave would silently
        # drop records at metrics_report's json.loads
        self._emit_lock = threading.Lock()
        # multi-host: only process 0 writes logs/files (every process
        # would otherwise duplicate records and race on the jsonl)
        self._primary = jax.process_index() == 0
        if not self._primary:
            return
        if cfg.metrics_path:
            self._jsonl = open(cfg.metrics_path, "a", buffering=1)
            self._write_run_header()
        if cfg.use_wandb:
            try:
                import wandb

                wandb.init(
                    project=cfg.wandb_project,
                    name=cfg.wandb_run_name,
                    config=run_config or cfg.to_dict(),  # train.py:151
                )
                self._wandb = wandb
            except Exception as e:
                print(f"[metrics] wandb unavailable ({type(e).__name__}); continuing without")

    def _write_run_header(self) -> None:
        """One identity record per logger lifetime (i.e. per process
        incarnation): joins records across supervisor relaunches. JSONL
        only — wandb carries the config natively via init."""
        try:
            device_kind = jax.local_devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
        header = {
            "record": "run_header",
            "ts": round(time.time(), 3),
            "config_hash": config_hash(self.cfg),
            "jax_version": jax.__version__,
            "device_kind": device_kind,
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
            "model": self.cfg.resolved_model().model,
        }
        self._jsonl.write(json.dumps(header) + "\n")

    # sentinel: "the caller did not sample memory — query it here";
    # distinct from None, which means "sampled and unavailable"
    _QUERY_MEMORY = object()

    def log_step(
        self,
        iter_num: int,
        loss: float,
        lr: float,
        tokens_per_sec: Optional[float] = None,
        extra: Optional[dict] = None,
        gpu_memory_mb=_QUERY_MEMORY,
    ) -> None:
        """Per-log_interval metrics (train.py:286-294), plus the natively
        measured tokens/sec the reference never recorded (SURVEY.md
        section 5.1; BASELINE.json north-star metric). ``extra`` carries
        run-health fields — anomaly-guard skipped_steps/rollbacks, the
        obs layer's step_time_ms/data_wait_frac/compile_events
        (train/trainer.py) — into the same record. ``gpu_memory_mb``
        lets a caller that already sampled :func:`device_memory_mb`
        (the trainer does, for its watermark gauge) pass the SAME value
        instead of paying a second memory_stats query per log."""
        if not self._primary:
            return
        print(f"iter {iter_num}: loss {loss:.4f}, lr {lr:.2e}")  # train.py:288
        payload = {
            "iter": iter_num,
            "loss": loss,
            "learning_rate": lr,
        }
        mem = (
            device_memory_mb()
            if gpu_memory_mb is MetricLogger._QUERY_MEMORY else gpu_memory_mb
        )
        if mem is not None:  # omitted, never a fake 0.0
            payload["gpu_memory"] = mem
        if tokens_per_sec is not None:
            payload["tokens_per_sec"] = round(tokens_per_sec, 1)
        if extra:
            payload.update(extra)
        self._emit(payload)

    def log_eval(self, iter_num: int, train_loss: float, val_loss: float) -> None:
        """Per-eval_interval metrics (train.py:297-304)."""
        if not self._primary:
            return
        print(
            f"step {iter_num}: train loss {train_loss:.4f}, val loss {val_loss:.4f}"
        )  # train.py:299
        self._emit({"iter": iter_num, "train_loss": train_loss, "val_loss": val_loss})

    def log_record(self, payload: dict) -> None:
        """Append one arbitrary record (e.g. ``{"record":
        "introspection", ...}`` from obs/introspect.py). JSONL + wandb,
        primary process only, ``ts`` added like every other record."""
        if not self._primary:
            return
        self._emit(dict(payload))

    def _emit(self, payload: dict) -> None:
        payload.setdefault("ts", round(time.time(), 3))
        with self._emit_lock:
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(payload) + "\n")
            if self._wandb is not None:
                self._wandb.log(payload)

    def finish(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
        if self._wandb is not None:
            self._wandb.finish()  # train.py:325
