"""Metric logging with pluggable sinks.

Replicates the reference's observability surface (train.py:286-304):
stdout prints in the same format, the same metric names and cadence
(``iter``/``loss``/``learning_rate``/``gpu_memory`` every log_interval;
``train_loss``/``val_loss`` every eval_interval), with sinks:
  - stdout (always),
  - JSONL append (replaces wandb as the durable record; always unless
    disabled),
  - wandb (optional, only if installed and enabled — the reference hard
    -requires it, train.py:15,151).

``gpu_memory`` keeps the reference's key name for drop-in dashboard
compatibility but reports the accelerator's (TPU) allocated bytes in MB.
"""

from __future__ import annotations

import json
from typing import Optional

import jax

from differential_transformer_replication_tpu.config import TrainConfig


def device_memory_mb() -> float:
    """Allocated device memory in MB (the reference logs
    torch.cuda.memory_allocated/1024**2, train.py:293)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        return stats.get("bytes_in_use", 0) / 1024**2
    except Exception:
        return 0.0


class MetricLogger:
    def __init__(self, cfg: TrainConfig, run_config: Optional[dict] = None):
        self.cfg = cfg
        self._jsonl = None
        self._wandb = None
        # multi-host: only process 0 writes logs/files (every process
        # would otherwise duplicate records and race on the jsonl)
        self._primary = jax.process_index() == 0
        if not self._primary:
            return
        if cfg.metrics_path:
            self._jsonl = open(cfg.metrics_path, "a", buffering=1)
        if cfg.use_wandb:
            try:
                import wandb

                wandb.init(
                    project=cfg.wandb_project,
                    name=cfg.wandb_run_name,
                    config=run_config or cfg.to_dict(),  # train.py:151
                )
                self._wandb = wandb
            except Exception as e:
                print(f"[metrics] wandb unavailable ({type(e).__name__}); continuing without")

    def log_step(
        self,
        iter_num: int,
        loss: float,
        lr: float,
        tokens_per_sec: Optional[float] = None,
        extra: Optional[dict] = None,
    ) -> None:
        """Per-log_interval metrics (train.py:286-294), plus the natively
        measured tokens/sec the reference never recorded (SURVEY.md
        section 5.1; BASELINE.json north-star metric). ``extra`` carries
        run-health counters (anomaly-guard skipped_steps/rollbacks,
        trainer.py) into the same record."""
        if not self._primary:
            return
        print(f"iter {iter_num}: loss {loss:.4f}, lr {lr:.2e}")  # train.py:288
        payload = {
            "iter": iter_num,
            "loss": loss,
            "learning_rate": lr,
            "gpu_memory": device_memory_mb(),
        }
        if tokens_per_sec is not None:
            payload["tokens_per_sec"] = round(tokens_per_sec, 1)
        if extra:
            payload.update(extra)
        self._emit(payload)

    def log_eval(self, iter_num: int, train_loss: float, val_loss: float) -> None:
        """Per-eval_interval metrics (train.py:297-304)."""
        if not self._primary:
            return
        print(
            f"step {iter_num}: train loss {train_loss:.4f}, val loss {val_loss:.4f}"
        )  # train.py:299
        self._emit({"iter": iter_num, "train_loss": train_loss, "val_loss": val_loss})

    def _emit(self, payload: dict) -> None:
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(payload) + "\n")
        if self._wandb is not None:
            self._wandb.log(payload)

    def finish(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
        if self._wandb is not None:
            self._wandb.finish()  # train.py:325
