"""Optimizer and LR schedule.

Replicates the reference recipe (train.py:236-251): AdamW with decoupled
weight decay applied to ALL parameters (torch applies it uniformly; we
deliberately do NOT exclude norms/biases, for parity), betas (0.9, 0.95),
global-norm gradient clipping at 1.0 BEFORE the optimizer step
(train.py:274-275), and the linear-warmup + cosine-decay schedule of
``CosineWarmupScheduler`` (train.py:109-123).

Parity notes:
  - torch steps the scheduler AFTER the optimizer, so optimizer step k
    uses the LR computed at count k starting from 0 — the FIRST step runs
    at lr = base * 0 / warmup = 0. optax's schedule-by-count reproduces
    this exactly (count starts at 0).
  - past max_steps the reference keeps following the cosine beyond pi
    (progress > 1); we replicate rather than clamp.
  - no GradScaler: bf16 on TPU needs no loss scaling (the reference's
    fp16 AMP machinery, train.py:251-279, is dropped by design).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from differential_transformer_replication_tpu.config import TrainConfig


def cosine_warmup_schedule(
    base_lr: float, warmup_steps: int, max_steps: int, min_lr: float
):
    """The exact formula of CosineWarmupScheduler.get_lr (train.py:116-123)."""

    def schedule(count):
        count = jnp.asarray(count, jnp.float32)
        warm = base_lr * count / max(warmup_steps, 1)
        progress = (count - warmup_steps) / max(max_steps - warmup_steps, 1)
        factor = 0.5 * (1.0 + jnp.cos(jnp.pi * progress))
        decay = min_lr + (base_lr - min_lr) * factor
        return jnp.where(count < warmup_steps, warm, decay)

    return schedule


def make_optimizer(cfg: TrainConfig) -> tuple[optax.GradientTransformation, callable]:
    """Returns (optimizer, schedule). The schedule is exposed separately so
    the trainer can log the LR (train.py:287-288)."""
    schedule = cosine_warmup_schedule(
        cfg.learning_rate, cfg.warmup_iters, cfg.max_iters, cfg.min_lr
    )
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),  # train.py:275
        optax.adamw(
            learning_rate=schedule,
            b1=cfg.beta1,
            b2=cfg.beta2,
            eps=1e-8,  # torch AdamW default
            weight_decay=cfg.weight_decay,  # applied to all params, as torch does
        ),
    )
    return tx, schedule
