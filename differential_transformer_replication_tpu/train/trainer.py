"""The training runtime: data build, train loop, eval, checkpointing.

This is the TPU-native counterpart of ``train()`` (train.py:141-325):
same recipe, same eval protocol, same logging cadence, plus resume —
with the eager per-batch Python loop replaced by a jitted step over
device-resident data.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from differential_transformer_replication_tpu.config import TrainConfig
from differential_transformer_replication_tpu.data import (
    TokenWindows,
    encode_corpus,
    split_tokens,
    train_bpe_tokenizer,
)
from differential_transformer_replication_tpu.train.anomaly import (
    TrainingDivergedError,
    snapshot_state,
)
from differential_transformer_replication_tpu.train.checkpoint import (
    AsyncCheckpointWriter,
    elastic_resume_info,
    load_checkpoint,
    resolve_resume_auto,
    save_checkpoint,
    save_step_checkpoint,
)
from differential_transformer_replication_tpu.train.watchdog import (
    StepWatchdog,
)
from differential_transformer_replication_tpu.obs import (
    NOOP_TRACER,
    Registry,
    SpanTracer,
    set_build_info,
    start_metrics_server,
)
from differential_transformer_replication_tpu.obs.introspect import (
    lambda_record,
    make_param_summary,
)
from differential_transformer_replication_tpu.train.metrics import (
    MetricLogger,
    config_hash,
    device_memory_mb,
)
from differential_transformer_replication_tpu.utils import ProfilerWindow, Throughput
from differential_transformer_replication_tpu.utils import faults
from differential_transformer_replication_tpu.train.step import (
    create_train_state,
    make_eval_many,
    make_train_step,
)


def estimate_loss(
    eval_many,
    params: dict,
    train_ds: TokenWindows,
    val_ds: TokenWindows,
    cfg: TrainConfig,
    rng: np.random.Generator,
    materialize=None,
) -> dict:
    """Mean loss over eval_iters batches from each split (train.py:125-139):
    train batches shuffled, val batches sequential from the start — the
    same draws the reference's two loaders produce.

    ``eval_many(params, xs, ys)`` evaluates ALL eval_iters stacked batches
    in one device call (a jitted scan, train/step.py:make_eval_many, or
    the pipeline microbatch stream, parallel/pipeline.py) and returns
    per-batch losses (or their scalar mean) — one host sync per split
    instead of one per batch. The rng draw sequence is identical to the
    old per-batch loop (one ``integers(size=B)`` call per train batch).

    ``materialize(ds, offs)`` turns (eval_iters, B) window offsets into a
    device batch dict. The trainer passes its ``_materialize`` so eval
    batches ride the SAME per-process-slice + global-assembly path as
    training batches on multi-process pods (every process computes
    identical offsets from the identically-seeded rng, so the slices are
    consistent); the default is the single-host device-side gather."""
    mat = materialize if materialize is not None else (lambda ds, offs: ds.batches(offs))
    out = {}
    for split, ds in (("train", train_ds), ("val", val_ds)):
        if split == "train":
            offs = np.stack(
                [
                    rng.integers(0, len(ds), size=cfg.micro_batch_size, dtype=np.int64)
                    for _ in range(cfg.eval_iters)
                ]
            )
        else:
            offs = np.stack(
                [
                    ds.sequential_offsets(k, cfg.micro_batch_size)
                    for k in range(cfg.eval_iters)
                ]
            )
        batch = mat(ds, offs)
        losses = np.asarray(
            jax.device_get(eval_many(params, batch["x"], batch["y"])), np.float64
        )
        out[split] = float(losses.mean())
    return out


def _cache_key(cfg: TrainConfig, source: str) -> str:
    """Key for the (token stream, tokenizer) cache pair: everything that
    determines them, over the corpus source ACTUALLY used (the
    tinystories->synthetic fallback must not poison the tinystories key).
    File-path datasets additionally key on mtime+size so edits invalidate.
    """
    import hashlib
    import os

    key_parts = [
        source, str(cfg.num_train_samples), str(cfg.vocab_size),
        str(cfg.min_frequency), str(cfg.seed), "v1",
    ]
    if os.path.exists(source):
        st = os.stat(source)
        key_parts += [str(st.st_mtime_ns), str(st.st_size)]
    return hashlib.sha1("|".join(key_parts).encode()).hexdigest()[:16]


def build_data(cfg: TrainConfig):
    """Corpus -> tokenizer -> token stream -> train/val window datasets
    (train.py:153-200).

    The encoded stream and its tokenizer are cached TOGETHER under a
    per-key directory (``tokenizer_dir/cache-<key>/``): corpus generation
    + BPE training + encoding cost minutes at the reference's 1M-document
    scale and are fully determined by the key. Pairing them in one
    directory means a cache hit can never load a mismatched tokenizer
    left in the shared dir by a different config. The freshly trained
    tokenizer is also saved to ``tokenizer_dir`` itself, matching the
    reference's artifact layout (train.py:49-50)."""
    import os

    from differential_transformer_replication_tpu.data.corpus import (
        load_corpus_resolved,
    )
    from differential_transformer_replication_tpu.data.tokenizer import (
        load_tokenizer,
    )

    # Resolve which corpus source the dataset name maps to. Only
    # "tinystories" is ambiguous (its network fallback depends on
    # HF-cache/egress state, corpus.py) — probe it with a 1-document load
    # (HF caches the dataset, so a later full load reuses the download).
    # "synthetic" and file paths resolve to themselves with no I/O.
    if cfg.dataset == "tinystories":
        _, source = load_corpus_resolved(cfg.dataset, 1, cfg.seed)
    else:
        source = cfg.dataset

    def cache_paths(src):
        d = os.path.join(cfg.tokenizer_dir, f"cache-{_cache_key(cfg, src)}")
        return d, os.path.join(d, "tokens.npy")

    cache_dir, tokens_path = cache_paths(source)
    if os.path.exists(tokens_path):
        tokenizer = load_tokenizer(cache_dir)
        tokens = np.load(tokens_path)
        print(f"Loaded {len(tokens)} cached tokens from {tokens_path}")
        vocab_size = tokenizer.get_vocab_size()
        print(f"Vocabulary size: {vocab_size}")  # train.py:161
    else:
        texts, source = load_corpus_resolved(
            cfg.dataset, cfg.num_train_samples, cfg.seed
        )
        # the full load may resolve differently than the probe (network
        # state can change between the two) — key on what was USED
        cache_dir, tokens_path = cache_paths(source)
        tokenizer = train_bpe_tokenizer(
            texts, cfg.vocab_size, cfg.min_frequency, cfg.tokenizer_dir
        )
        vocab_size = tokenizer.get_vocab_size()
        print(f"Vocabulary size: {vocab_size}")  # train.py:161
        tokens = encode_corpus(tokenizer, texts)
        # Build the WHOLE cache entry (tokenizer files + tokens) in a
        # scratch dir, then rename it into place: a crash or a concurrent
        # builder can never leave a half-written entry that matches the
        # key. If another process won the rename race, adopt its entry.
        tmp_dir = f"{cache_dir}.tmp.{os.getpid()}"
        os.makedirs(tmp_dir, exist_ok=True)
        tokenizer.save_model(tmp_dir)
        with open(os.path.join(tmp_dir, "tokens.npy"), "wb") as f:
            np.save(f, tokens)
        try:
            os.rename(tmp_dir, cache_dir)
        except OSError:
            import shutil

            shutil.rmtree(tmp_dir, ignore_errors=True)
    print(f"Total tokens: {len(tokens)}")  # train.py:174
    train_tokens, val_tokens = split_tokens(tokens, cfg.val_fraction)
    block = cfg.model.block_size
    return (
        tokenizer,
        vocab_size,
        TokenWindows(train_tokens, block),
        TokenWindows(val_tokens, block),
    )


def train(cfg: TrainConfig) -> dict:
    """Run the full recipe; returns the final train state."""
    from differential_transformer_replication_tpu.parallel.multihost import (
        gather_to_host,
        initialize as distributed_initialize,
        is_primary,
    )

    distributed_initialize()  # no-op single-process (multihost.py)
    print(f"Using devices: {jax.devices()}")
    # chaos-test fault injection (utils/faults.py); inert unless armed
    # via cfg.faults or the DTX_FAULTS env var
    faults.arm(cfg.faults)

    tokenizer, vocab_size, train_ds, val_ds = build_data(cfg)
    cfg = cfg.replace(vocab_size=vocab_size)
    from differential_transformer_replication_tpu.data.tokenizer import (
        check_tokenizer_matches,
        tokenizer_fingerprint,
    )

    tok_fp = tokenizer_fingerprint(tokenizer)
    ckpt_auto_skipped = 0
    # auto-resolution digest-verifies its winner moments before the
    # load; skip the redundant second full-file hash there (explicit
    # --resume-from paths still verify at load)
    resume_verify = True
    if cfg.resume_from == "auto":
        # Verified resume: newest checkpoint that passes manifest
        # verification, falling back to older ones — a crash mid-save
        # (uncertified dir) or a bit-rotted file can never wedge the
        # restart loop (train/checkpoint.py:resolve_resume_auto).
        resolved, skipped = resolve_resume_auto(cfg)
        ckpt_auto_skipped = len(skipped)
        if is_primary():
            for p, why in skipped:
                print(f"[ckpt] skipping unverified checkpoint {p}: {why}")
            if resolved is None:
                print("[ckpt] --resume-from auto: no verified checkpoint "
                      "found; starting fresh")
            else:
                print(f"[ckpt] --resume-from auto: resuming from {resolved}")
        cfg = cfg.replace(resume_from=resolved)
        resume_verify = resolved is None
    resume_info = None  # elastic-resume facts (mesh/batch/consumed)
    if cfg.resume_from:
        # Resume must continue on the SAME token stream: if the cache
        # entry was lost and the corpus re-resolved to different content,
        # every id is still valid and training silently continues on a
        # differently-tokenized stream — then overwrites the checkpoint,
        # destroying the evidence. Compare content fingerprints up front
        # (older checkpoints without one degrade to the size check).
        import os as _os

        from differential_transformer_replication_tpu.train.checkpoint import (
            read_meta,
        )

        # a meta-less dir leaves resume_info None, which is safe: the
        # later load_checkpoint -> read_meta raises CheckpointError for
        # it, so no resume can proceed without passing through
        # elastic_resume_info here first
        meta_path = _os.path.join(cfg.resume_from, "meta.json")
        if _os.path.exists(meta_path):
            meta = read_meta(cfg.resume_from)
            # Elastic resume (train/checkpoint.py): assert param-shape
            # compatibility up front (a typed error, not a deep flax
            # shape mismatch) and recover the sampler's exact position
            # in consumed windows — a preemption that returns a
            # DIFFERENT device count (or a retuned global batch) still
            # resumes onto the new mesh, bit-exact where the batch
            # math allows. Raises ElasticResumeError when exactness is
            # impossible and --allow-inexact-resume was not given.
            resume_info = elastic_resume_info(meta, cfg)
            if is_primary() and resume_info["elastic"]:
                print(
                    f"[elastic] resuming a checkpoint trained on mesh "
                    f"{resume_info['saved_mesh']} onto "
                    f"{dataclasses.asdict(cfg.mesh)} "
                    f"({'exact' if resume_info['exact'] else 'INEXACT'} "
                    f"sampler fast-forward from "
                    f"{resume_info['consumed_windows']} consumed windows)"
                )
            # compare against the CHECKPOINT's recorded vocab size, not
            # cfg.vocab_size — the latter was just overwritten from this
            # very tokenizer (cfg.replace above), which made the size leg
            # vacuous: a wrong-size tokenizer then only failed later on
            # an unhelpful flax shape mismatch (ADVICE r5 finding 1)
            saved_cfg = meta.get("config", {})
            # the TOP-LEVEL vocab_size is the one save_checkpoint records
            # from the live run (trainer resolves the tokenizer's vocab
            # into it; the nested model.vocab_size keeps its un-resolved
            # construction-time default)
            recorded_vocab = (
                saved_cfg.get("vocab_size")
                or (saved_cfg.get("model") or {}).get("vocab_size")
                or cfg.vocab_size  # very old meta: degrade to vacuous
            )
            check_tokenizer_matches(
                tokenizer, recorded_vocab,
                meta.get("tokenizer_fingerprint"), context=cfg.resume_from,
            )

    logger = MetricLogger(cfg)

    # -- observability (obs/): registry + sidecar + host span tracer --
    # The registry always exists (instrumentation is unconditional and
    # cheap — a few lock-guarded float updates per iteration); the
    # sidecar exporter and the Chrome span trace are opt-in knobs.
    registry = Registry()
    # process identity on the sidecar's /metrics (same build_info gauge
    # roles as router/replica, so an aggregated fleet scrape that
    # includes a training sidecar stays attributable)
    set_build_info(registry, role="trainer",
                   config_hash=config_hash(cfg),
                   version=jax.__version__)
    obs_step_hist = registry.histogram(
        "train_step_seconds",
        "Wall time of one train-loop iteration, host-observed "
        "(data wait + dispatch + any blocking).",
    )
    obs_data_hist = registry.histogram(
        "train_data_wait_seconds",
        "Host time assembling the next batch before dispatch.",
    )
    obs_stall_gauge = registry.gauge(
        "train_data_stall_ratio",
        "Fraction of recent loop wall time spent waiting on data.",
    )
    obs_mem_gauge = registry.gauge(
        "train_device_memory_peak_mb",
        "High-water mark of allocated device memory (MB).",
    )
    obs_compile_counter = registry.counter(
        "train_compile_events_total",
        "Compilation-cache entries of the jitted train step "
        "(steady state must stay at 1 — a growing count means "
        "something retraces).",
    )
    obs_iter_counter = registry.counter(
        "train_iterations_total", "Optimizer steps completed."
    )
    obs_anomaly_counter = registry.counter(
        "train_anomaly_events_total",
        "Anomaly-guard interventions (train/anomaly.py).",
        labelnames=("kind",),
    )
    obs_ckpt_save_hist = registry.histogram(
        "ckpt_save_seconds",
        "Wall time of one checkpoint save job (serialize + write + "
        "certify + GC), wherever it ran (writer thread or inline).",
    )
    obs_ckpt_blocked_hist = registry.histogram(
        "ckpt_blocked_seconds",
        "Train-loop wall time blocked on checkpointing per periodic "
        "snapshot: back-pressure waiting for a still-in-flight async "
        "save (steady state ~0; growing = the disk cannot keep up "
        "with ckpt_interval).",
    )
    obs_ckpt_verify_failures = registry.counter(
        "ckpt_verify_failures_total",
        "Checkpoints that failed integrity verification (digest "
        "mismatch, truncation, missing manifest) and were skipped "
        "during resume resolution.",
    )
    obs_ckpt_save_failures = registry.counter(
        "ckpt_save_failures_total",
        "Periodic step-checkpoint saves that failed (the run continues "
        "but is less protected; a growing count means the checkpoint "
        "storage is broken).",
    )
    obs_watchdog_fires = registry.counter(
        "train_watchdog_fires_total",
        "Step-deadline watchdog fires (train/watchdog.py): a training "
        "iteration hung past step_deadline_s, or a peer's heartbeat "
        "silence coordinated an abort. The process exits with the "
        "hang code right after incrementing, so any scrape showing "
        ">0 is the post-mortem of a dying incarnation.",
    )
    obs_heartbeat_age = registry.gauge(
        "train_heartbeat_age_seconds",
        "Seconds since each peer process's heartbeat record last "
        "changed, judged by this host's monotonic clock "
        "(parallel/heartbeat.py). Healthy: ~heartbeat_interval_s; "
        "growing toward heartbeat_timeout_s: that peer is dying.",
        labelnames=("peer",),
    )
    if ckpt_auto_skipped:
        obs_ckpt_verify_failures.inc(ckpt_auto_skipped)
    tracer = (
        SpanTracer(cfg.trace_path, process_name="trainer")
        if cfg.trace_path and is_primary() else NOOP_TRACER
    )
    metrics_server = None
    if cfg.metrics_port > 0 and is_primary():
        metrics_server = start_metrics_server(registry, cfg.metrics_port)
        print(
            f"[obs] Prometheus sidecar: "
            f"http://0.0.0.0:{metrics_server.server_address[1]}/metrics"
        )

    if cfg.mesh.pipeline > 1:
        # Pipeline-parallel path: GPipe schedule over the pipeline axis
        # (parallel/pipeline.py); eval runs through the same pipeline.
        from differential_transformer_replication_tpu.parallel import create_mesh
        from differential_transformer_replication_tpu.parallel.pipeline import (
            create_pipeline_train_state,
            make_pipeline_eval_many,
            make_pipeline_train_step,
            pipeline_state_sharding,
        )

        mesh = create_mesh(cfg.mesh)
        print(f"Mesh: {dict(mesh.shape)}")
        state = create_pipeline_train_state(jax.random.PRNGKey(cfg.seed), cfg, mesh)
        best_val_loss = float("inf")
        if cfg.resume_from:
            host_state = gather_to_host(state)
            host_state, best_val_loss = load_checkpoint(cfg.resume_from, cfg, host_state, verify=resume_verify)
            sh = pipeline_state_sharding(host_state, mesh)
            state = jax.tree_util.tree_map(jax.device_put, host_state, sh)
            print(f"Resumed from {cfg.resume_from} at iter {int(jax.device_get(state['step']))}")
        train_step = make_pipeline_train_step(cfg, mesh, state)
        # eval feeds all eval_iters batches through the pipeline as ONE
        # microbatch stream: bubble amortized (P-1)/(K+P-1) instead of
        # (P-1)/P per batch (VERDICT r1 item 7)
        eval_many = make_pipeline_eval_many(cfg, mesh)
    elif cfg.mesh.n_devices > 1:
        # Sharded path: mesh + partitioned step (the DDP/NCCL replacement).
        from differential_transformer_replication_tpu.parallel import (
            create_mesh,
            make_sharded_train_step,
            shard_state,
        )
        from differential_transformer_replication_tpu.parallel.dp_step import (
            create_sharded_train_state,
        )

        mesh = create_mesh(cfg.mesh)
        # threaded into eval too; model_forward ignores it unless the mesh
        # has a >1 sequence axis (ring.use_ring), keeping eval and train
        # on the same attention path by construction
        eval_mesh = mesh
        print(f"Mesh: {dict(mesh.shape)}")
        state = create_sharded_train_state(jax.random.PRNGKey(cfg.seed), cfg, mesh)
        best_val_loss = float("inf")
        if cfg.resume_from:
            # the freshly-initialized state supplies the target pytree; on
            # multi-process pods its fsdp/tensor shards live on other
            # hosts' devices, so the host copy must be the collective
            # gather, and the re-placement below relies on device_put
            # accepting a global sharding when every process holds the
            # same full host value (which load_checkpoint guarantees)
            host_state = gather_to_host(state)
            host_state, best_val_loss = load_checkpoint(cfg.resume_from, cfg, host_state, verify=resume_verify)
            state = shard_state(host_state, mesh)
            print(f"Resumed from {cfg.resume_from} at iter {int(jax.device_get(state['step']))}")
        train_step = make_sharded_train_step(cfg, mesh, state)
    else:
        eval_mesh = None
        state = create_train_state(jax.random.PRNGKey(cfg.seed), cfg)
        best_val_loss = float("inf")
        if cfg.resume_from:
            state, best_val_loss = load_checkpoint(cfg.resume_from, cfg, state, verify=resume_verify)
            print(f"Resumed from {cfg.resume_from} at iter {int(state['step'])}")
        train_step = make_train_step(cfg)
    if cfg.mesh.pipeline <= 1:
        eval_many = make_eval_many(cfg, mesh=eval_mesh)

    # -- consumed-window accounting (elastic resume) -------------------
    # The epoch sampler's position is tracked in WINDOWS CONSUMED, not
    # derived from step arithmetic: a resumed run whose global batch
    # size changed (elastic resume) would otherwise fast-forward the
    # permutation to the wrong place. The base comes from the
    # checkpoint's recorded consumed_windows (elastic_resume_info);
    # everything after the base advances under THIS run's batch math.
    start_iter = int(jax.device_get(state["step"]))
    consumed_base_iter = start_iter
    if resume_info is not None and resume_info["consumed_windows"] is not None:
        consumed_base = resume_info["consumed_windows"]
    else:
        consumed_base = (
            start_iter * cfg.grad_acc_steps * cfg.micro_batch_size
        )

    def consumed_at(it: int) -> int:
        """Windows consumed once iteration ``it`` of THIS run has
        completed — the sampler fast-forward anchor and the
        consumed_windows every checkpoint save records."""
        return consumed_base + (it - consumed_base_iter) * (
            cfg.grad_acc_steps * cfg.micro_batch_size
        )

    if cfg.checkpoint_min_interval_s > 0:
        # The throttle's deferred-improvement snapshot pins a SECOND full
        # train state in HBM until the next write or exit; surface the
        # headroom risk at startup instead of OOM-ing a run that fit
        # without the throttle (advisor, round 4). Count DEVICE-0 shard
        # bytes, not global bytes — on sharded runs the snapshot adds only
        # each device's own shard.
        dev0 = jax.local_devices()[0]

        def _dev0_bytes(leaf):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                return sum(
                    s.data.nbytes for s in shards if s.device == dev0
                )
            return getattr(leaf, "nbytes", 0)

        state_bytes = sum(
            _dev0_bytes(leaf) for leaf in jax.tree_util.tree_leaves(state)
        )
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:  # platforms without memory_stats (e.g. CPU)
            stats = {}
        limit = stats.get("bytes_limit", 0)
        in_use = stats.get("bytes_in_use", 0)
        # in_use already counts the live state; the deferred snapshot pins
        # exactly ONE additional copy
        if limit and in_use + state_bytes > 0.92 * limit:
            import warnings

            warnings.warn(
                "checkpoint_min_interval_s > 0 keeps an on-device snapshot "
                f"of the full train state (~{state_bytes / 2**20:.0f} MiB) "
                "while a best-checkpoint write is deferred; estimated HBM "
                f"({(in_use + state_bytes) / 2**20:.0f} of "
                f"{limit / 2**20:.0f} MiB) leaves little headroom — a run "
                "that fits without the throttle may OOM with it. Set "
                "--checkpoint-min-interval-s 0 if memory-tight",
                stacklevel=2,
            )

    data_rng = np.random.default_rng(cfg.seed)
    eval_rng = np.random.default_rng(cfg.seed + 1)

    # Multi-process pods: every host draws the SAME offsets (the samplers
    # are seeded identically), takes its own disjoint batch-column slice,
    # gathers those windows host-side, and
    # jax.make_array_from_process_local_data assembles the global batch —
    # the working DistributedSampler replacement (train.py:8-10). Single
    # process keeps the device-resident gather.
    from differential_transformer_replication_tpu.parallel.multihost import (
        global_batch as assemble_global,
        local_batch_slice,
        process_count,
    )

    multihost_data = process_count() > 1 and cfg.mesh.n_devices > 1

    def _materialize(ds, offs: np.ndarray) -> dict:
        # (A|K, B) offsets -> device batch dict; used by BOTH the training
        # draw and eval (estimate_loss), so every data path is per-process
        # sliced + globally assembled on pods
        if multihost_data:
            start, per = local_batch_slice(cfg.micro_batch_size)
            local = ds.host_batches(offs[:, start : start + per])
            return assemble_global(local, mesh)
        return ds.batches(offs)

    if cfg.sampler == "epoch":
        # exact DataLoader-style epoch shuffle (train.py:184-191) via the
        # native O(1)-memory permutation
        from differential_transformer_replication_tpu.data.native import (
            EpochPermutation,
        )

        perm = EpochPermutation(len(train_ds), cfg.seed)
        # fast-forward past windows already consumed before a resume, so
        # the once-per-epoch guarantee survives checkpoint restarts —
        # from the checkpoint's RECORDED consumed count (consumed_at),
        # so an elastic resume under a changed global batch size keeps
        # the permutation position exact
        perm.epoch, perm.cursor = divmod(
            consumed_at(start_iter), len(train_ds)
        )

        def draw_batch():
            offs = perm.take(cfg.grad_acc_steps * cfg.micro_batch_size)
            return _materialize(
                train_ds, offs.reshape(cfg.grad_acc_steps, cfg.micro_batch_size)
            )
    elif cfg.sampler == "replacement":
        def draw_batch():
            offs = data_rng.integers(
                0, len(train_ds),
                size=(cfg.grad_acc_steps, cfg.micro_batch_size),
                dtype=np.int64,
            )
            return _materialize(train_ds, offs)
    else:
        raise ValueError(f"unknown sampler {cfg.sampler!r}")
    dropout_key = jax.random.PRNGKey(cfg.seed + 2)
    model_cfg = cfg.resolved_model()
    use_dropout = model_cfg.dropout > 0.0

    # Paper-level introspection (obs/introspect.py): jitted per-layer
    # lambda + param-norm summary fetched every eval interval, so the
    # lambda-evolution figure is reproducible from metrics.jsonl
    # (tools/lambda_report.py). The pipeline path stacks params per
    # stage — a layout the summary does not speak — so it is skipped
    # there, like the anomaly guard.
    param_summary = (
        make_param_summary(model_cfg) if cfg.mesh.pipeline <= 1 else None
    )

    # Continuous on-device profiling (obs/device_profile.py): every
    # profile_every iterations one step is wrapped in a jax.profiler
    # capture, parsed off-loop, and published as device_* gauges,
    # {"record":"device_profile"} metrics.jsonl rows, and a stitchable
    # device-lane trace. The FLOPs/HBM estimates feed the derived
    # device_mfu gauge with bench.py's exact 6*N*D accounting, so the
    # continuous samples and bench rounds are directly comparable.
    device_prof = None
    if cfg.profile_every > 0 and is_primary():
        from differential_transformer_replication_tpu.models import (
            param_count,
        )
        from differential_transformer_replication_tpu.obs import xprof
        from differential_transformer_replication_tpu.obs.device_profile import (
            DeviceProfileSampler,
        )

        n_params = param_count(state["params"])
        n_embed = xprof.embedding_param_count(
            model_cfg.model, model_cfg.vocab_size, model_cfg.n_embd,
            model_cfg.block_size,
        )
        tokens_per_step = (
            cfg.micro_batch_size * cfg.grad_acc_steps * model_cfg.block_size
        )
        # the parsed plane is ONE device's timeline and the MFU
        # denominator is ONE chip's peak, so the numerator must be the
        # PER-CHIP share of the step's work — on an n-device mesh each
        # chip executes ~1/n of the global FLOPs (data splits the
        # batch, tensor/fsdp/pipeline split the math), and the same
        # division approximates per-chip HBM traffic (right for
        # sharded params; an underestimate for DP-replicated ones,
        # which re-read the full set per chip — roofline-order only)
        n_dev = max(1, cfg.mesh.n_devices)
        device_prof = DeviceProfileSampler(
            every=cfg.profile_every,
            spool_dir=cfg.resolved_profile_spool(),
            registry=registry,
            sink=logger.log_record,
            jsonl_path=None,  # rows ride the run's own metrics.jsonl
            tracer=tracer,
            process="trainer",
            flops_per_step=xprof.train_flops_per_step(
                n_params, n_embed, tokens_per_step
            ) / n_dev,
            hbm_bytes_per_step=(
                xprof.train_hbm_bytes_per_step(n_params) / n_dev
            ),
        )

    def _compile_entries():
        """Compile-cache size of the jitted step (None when the step
        wrapper does not expose one): steady state must hold at 1; a
        growing count is the retrace pathology the zero-recompile pins
        (tests/test_obs.py) guard against."""
        cache_size = getattr(train_step, "_cache_size", None)
        if cache_size is None:
            return None
        try:
            return int(cache_size())
        except Exception:
            return None

    # -- resilience layer (train/watchdog.py, parallel/heartbeat.py) --
    # Both are pure HOST-side daemon threads: they never touch traced
    # code, so the compile count stays pinned at 1 with them enabled
    # (tests/test_watchdog.py). The watchdog object also exists when
    # only the heartbeat is configured — a dead peer trips it directly
    # (coordinated abort), deadline monitor or not.
    watchdog = None
    heartbeat = None
    wd_warm = False  # becomes True once the first iteration compiled
    hb_iter = {"i": start_iter}  # host iter, read by the publisher
    if cfg.step_deadline_s > 0 or cfg.heartbeat_dir:
        watchdog = StepWatchdog(
            cfg.step_deadline_s,
            report_path=cfg.resolved_hang_report_path(),
            sink=logger.log_record,
            fires_counter=obs_watchdog_fires,
            context={
                "compile_events": _compile_entries,
                "device_profile": lambda: getattr(
                    device_prof, "last_record", None
                ),
                "process_index": jax.process_index,
            },
        )
    if cfg.heartbeat_dir:
        from differential_transformer_replication_tpu.parallel.heartbeat import (
            FileHeartbeatTransport,
            Heartbeat,
        )

        def _peer_dead(peer: int, age: float) -> None:
            # a silent peer means the next collective wedges every
            # surviving host: fire the watchdog NOW instead of waiting
            # out the step deadline inside a psum
            watchdog.trip(
                f"peer process {peer} heartbeat silent for {age:.1f}s "
                f"(timeout {cfg.heartbeat_timeout_s:.1f}s): "
                "coordinated abort"
            )

        heartbeat = Heartbeat(
            FileHeartbeatTransport(cfg.heartbeat_dir),
            process_index=jax.process_index(),
            num_processes=process_count(),
            interval_s=cfg.heartbeat_interval_s,
            timeout_s=cfg.heartbeat_timeout_s,
            iter_supplier=lambda: hb_iter["i"],
            on_dead=_peer_dead,
            age_gauge=obs_heartbeat_age,
        )
        watchdog.add_context(heartbeat_ages=heartbeat.peer_ages)

    # Anomaly guard (train/anomaly.py): the jitted step skips bad
    # updates on-device; the host side here keeps a periodic good-state
    # snapshot, rolls back to it when badness persists, and aborts when
    # rollbacks stop helping. Pipeline runs use a different step
    # (parallel/pipeline.py) that does not carry the guard state.
    guard_on = cfg.anomaly_guard and cfg.mesh.pipeline <= 1
    if cfg.anomaly_guard and cfg.mesh.pipeline > 1 and is_primary():
        print("[anomaly] guard is unsupported on the pipeline path; disabled")
    # the pipeline step's jit signature declares only {"x","y"} batches
    # (parallel/pipeline.py) — NaN injection is train-step-only, like
    # the guard that exists to catch it
    nan_fault_armed = faults.nan_armed() and cfg.mesh.pipeline <= 1
    if faults.nan_armed() and cfg.mesh.pipeline > 1 and is_primary():
        print("[faults] nan injection is unsupported on the pipeline "
              "path; disabled")
    rollbacks = 0

    # Durable rotating step checkpoints (train/ckpt_writer.py): every
    # ckpt_interval iterations the state is snapshotted to host and a
    # certified `step-NNNNNNNN` dir is written + GC'd — from a
    # background writer thread when ckpt_async (the loop then blocks
    # only for the device->host snapshot, with back-pressure if the
    # previous save is still in flight). The writer exists on the
    # primary only; other ranks just participate in the snapshot's
    # collective gather.
    ckpt_root = cfg.resolved_ckpt_dir()
    ckpt_writer = None
    ckpt_last_save_s = None  # sync-path mirror of writer.last_save_s
    if cfg.ckpt_interval > 0:
        if cfg.ckpt_keep_last < 1:
            raise ValueError(
                "ckpt_keep_last must be >= 1 when ckpt_interval > 0, "
                f"got {cfg.ckpt_keep_last}"
            )
        if cfg.ckpt_async and is_primary():
            ckpt_writer = AsyncCheckpointWriter(
                save_hist=obs_ckpt_save_hist,
                blocked_hist=obs_ckpt_blocked_hist,
            )

    print("Starting training...")
    t0 = time.time()
    tokens_seen = 0
    throughput = Throughput()
    # profile a short steady-state window past compile + warmup, relative
    # to wherever this run starts (fresh or resumed)
    profiler = ProfilerWindow(
        cfg.profile_dir, start=int(jax.device_get(state["step"])) + 10
    )
    # Preemption safety (SURVEY.md section 5.3 — the reference has none):
    # SIGTERM requests a graceful stop; the finally block below writes a
    # resumable last-state checkpoint on ANY exit (preemption, Ctrl-C,
    # crash mid-run, or normal completion), so `--resume-from
    # <last_checkpoint_path>` always continues from the latest step.
    stop_requested = {"flag": False}

    def _on_sigterm(signum, frame):
        del signum, frame
        stop_requested["flag"] = True

    def _agreed_stop(iter_num: int) -> bool:
        """Whether to break the train loop THIS iteration. Single-process:
        the local SIGTERM flag, checked every iteration. Multi-process:
        the flag is OR-reduced across ranks at log_interval boundaries
        (where logging already forces a host sync), so every rank breaks
        at the SAME iteration — a rank leaving the loop early while peers
        still run train_step psums would mismatch collectives and hang
        the pod. Scheduler preemptions deliver SIGTERM to each rank at
        slightly different times; the agreement absorbs that skew at the
        cost of up to log_interval extra steps of grace period."""
        if process_count() == 1:
            return stop_requested["flag"]
        if iter_num % cfg.log_interval != 0:
            return False
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.float32(1.0 if stop_requested["flag"] else 0.0)
        )
        return bool(np.asarray(flags).sum() > 0)

    import signal

    prev_handler = None
    try:
        prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (tests); SIGTERM stays default
    # Host-side iteration counter: the device `state["step"]` advances by
    # exactly 1 per call, and reading it back would force a host-device
    # sync every iteration, breaking async dispatch pipelining.
    iter_num = int(jax.device_get(state["step"]))
    metrics = None  # last step's metrics; gates the rescue save below
    last_ckpt_path = cfg.resolved_last_checkpoint_path()
    best_snapshot = None  # device-side best state not yet written to disk
    best_snapshot_iter = 0  # its iteration (consumed-window accounting)
    # seeded at loop entry: "at most one best write per interval" holds
    # from the start (interval 0 still writes on every improvement).
    # monotonic: a backward wall-clock step (NTP) must not defer writes
    last_best_write = time.monotonic() - cfg.checkpoint_min_interval_s
    # set by the except below — NOT derived from sys.exc_info(), which
    # would also be truthy when train() runs inside a caller's exception
    # handler (e.g. a retry wrapper) and would wrongly suppress the
    # multi-process rescue save on a clean run
    crashed = False
    # the guard's rollback target: seeded at loop entry so one always
    # exists, refreshed every anomaly_snapshot_interval good iterations.
    # Like the throttle snapshot above, it pins ONE extra train state in
    # HBM (device-0-shard-sized on sharded runs).
    good_snapshot = snapshot_state(state) if guard_on else None
    snapshot_iter = iter_num
    # per-log-interval telemetry accumulators (flushed into each
    # log_step record's extra fields and the registry gauges)
    obs_acc_step = obs_acc_data = 0.0
    obs_acc_n = 0
    ckpt_acc_blocked = 0.0  # back-pressure seconds since the last log
    # last observed in-state skip total: the Prometheus counter must
    # only ever move by POSITIVE deltas (a rollback rewinds the guard
    # state — and with it metrics["skipped"] — but an exported counter
    # that decreases reads as a process restart to rate()/increase())
    obs_prev_skipped = 0
    try:
        while iter_num < cfg.max_iters:
            if _agreed_stop(iter_num):
                if is_primary():
                    print(f"SIGTERM received: stopping at iter {iter_num}")
                break
            faults.fire(iter_num)  # injected raise/SIGTERM/SIGKILL points
            if watchdog is not None and wd_warm:
                # armed across the step's dispatch and the host syncs
                # that follow it; legitimately slow sections (eval,
                # checkpoint writes) run disarmed below. The FIRST
                # iteration of this process runs unarmed: its dispatch
                # traces + compiles the step (tens of seconds to
                # minutes), which is slow-but-alive, not a hang —
                # deadlining it would turn every cold start and every
                # supervised relaunch into a false watchdog fire.
                watchdog.arm(iter_num)
            # chaos stalls (train_hang / collective_skew) land INSIDE
            # the armed window — they simulate a wedged or lagging loop
            faults.train_stall(iter_num)
            if faults.corrupt_params_at(iter_num):
                # simulated state corruption (bitflip-class fault): NaN a
                # param leaf — batch skipping cannot cure this; only the
                # guard's rollback recovers the run
                leaves, treedef = jax.tree_util.tree_flatten(state["params"])
                leaves[0] = leaves[0] * jnp.float32(jnp.nan)
                state["params"] = jax.tree_util.tree_unflatten(treedef, leaves)
            t_iter = time.perf_counter()
            with tracer.span("data_wait", iter=iter_num):
                batch = draw_batch()
            data_wait = time.perf_counter() - t_iter
            if nan_fault_armed:
                # present in EVERY batch while armed, so the compiled
                # step's input structure never changes (train/step.py)
                scale = np.nan if faults.poison_at(iter_num) else 1.0
                batch["poison"] = np.full(
                    (cfg.grad_acc_steps,), scale, np.float32
                )
            rng = jax.random.fold_in(dropout_key, iter_num) if use_dropout else None
            # non-due steps pay one integer compare here; a due step
            # opens a capture window around exactly this dispatch
            capturing = (
                device_prof is not None
                and device_prof.maybe_begin(iter_num)
            )
            with tracer.span("dispatch", iter=iter_num):
                state, metrics = train_step(state, batch, rng)
            iter_num += 1
            hb_iter["i"] = iter_num  # heartbeat telemetry (off-loop read)
            if capturing:
                # closes the window (blocking on the step's loss so the
                # device work is inside it) and hands the trace to the
                # off-loop parse worker
                device_prof.end(sync=metrics["loss"])
            profiler.step(iter_num, sync=metrics["loss"])
            tokens_seen += cfg.micro_batch_size * cfg.grad_acc_steps * model_cfg.block_size

            if guard_on and iter_num % cfg.anomaly_check_interval == 0:
                # one replicated-scalar read: every rank computes the same
                # streak (the bad flag is a global value, train/anomaly
                # .py), so rollback/abort decisions agree with no
                # collective. This blocks on the step's completion —
                # anomaly_check_interval amortizes that pipeline bubble.
                with tracer.span("block", what="anomaly_streak"):
                    # deliberate sync, amortized by anomaly_check_interval
                    streak = int(jax.device_get(metrics["bad_streak"]))  # graftlint: disable=GL202 (anomaly_check_interval cadence)
                if streak == 0:
                    if iter_num - snapshot_iter >= cfg.anomaly_snapshot_interval:
                        good_snapshot = snapshot_state(state)
                        snapshot_iter = iter_num
                elif streak >= cfg.anomaly_rollback_after:
                    rollbacks += 1
                    if rollbacks > cfg.anomaly_max_rollbacks:
                        raise TrainingDivergedError(
                            f"{rollbacks - 1} rollback(s) did not recover "
                            f"the run: still {streak} consecutive bad "
                            f"steps at iter {iter_num}. Aborting without "
                            "overwriting the last good checkpoint."
                        )
                    if is_primary():
                        print(
                            f"[anomaly] {streak} consecutive bad steps at "
                            f"iter {iter_num}: rolling back to iter "
                            f"{snapshot_iter} (rollback {rollbacks}/"
                            f"{cfg.anomaly_max_rollbacks})"
                        )
                    if watchdog is not None:
                        # the full-state restore below is a legitimate
                        # slow recovery section, not a hang — it must
                        # not run against the deadline armed at the top
                        # of this iteration (and the completed dispatch
                        # already proved the step compiled)
                        watchdog.disarm()
                        wd_warm = True
                    # an in-HBM resume: restore the snapshot (copy — the
                    # donated step must not consume it) and rewind the
                    # epoch sampler to the matching position, exactly the
                    # checkpoint-resume fast-forward. The replacement
                    # sampler is stateless draws and simply continues.
                    state = snapshot_state(good_snapshot)
                    iter_num = snapshot_iter
                    metrics = None
                    if cfg.sampler == "epoch":
                        perm.epoch, perm.cursor = divmod(
                            consumed_at(iter_num), len(train_ds)
                        )
                    continue

            if watchdog is not None:
                # the slow tails below (checkpoint write, eval) are
                # legitimate; only the step+sync window is deadlined
                watchdog.disarm()
                wd_warm = True  # compile is done: deadline from now on

            # host-observed iteration accounting: wall time of the whole
            # loop body (dispatch-pipelined, so this is NOT device step
            # time — it is what the user waits for) and the data-wait
            # share of it. A rolled-back iteration skips this (its work
            # was discarded with the state).
            step_wall = time.perf_counter() - t_iter
            obs_step_hist.observe(step_wall)
            obs_data_hist.observe(data_wait)
            obs_iter_counter.inc()
            obs_acc_step += step_wall
            obs_acc_data += data_wait
            obs_acc_n += 1

            if cfg.ckpt_interval > 0 and iter_num % cfg.ckpt_interval == 0:
                # periodic certified step checkpoint: the snapshot
                # (collective gather -> host numpy) happens here on the
                # loop; serialization/IO/GC run on the writer thread
                # when async. A failed save must not kill a healthy
                # run — it is counted and printed instead.
                with tracer.span("ckpt_snapshot", iter=iter_num):
                    t_ck = time.perf_counter()
                    try:
                        blocked = save_step_checkpoint(
                            ckpt_root, state, best_val_loss, cfg,
                            tokenizer_fingerprint=tok_fp,
                            writer=ckpt_writer,
                            keep_last=cfg.ckpt_keep_last,
                            keep_every=cfg.ckpt_keep_every,
                            consumed_windows=consumed_at(iter_num),
                        )
                        ckpt_acc_blocked += blocked
                        if ckpt_writer is None and is_primary():
                            # sync path: the whole save ran inline here
                            ckpt_last_save_s = time.perf_counter() - t_ck
                            obs_ckpt_save_hist.observe(ckpt_last_save_s)
                    except Exception as e:  # noqa: BLE001
                        obs_ckpt_save_failures.inc()
                        if is_primary():
                            print(f"[ckpt] step-checkpoint save failed "
                                  f"at iter {iter_num} (continuing): {e!r}")

            if iter_num % cfg.log_interval == 0:
                extra = {}
                if watchdog is not None and wd_warm:
                    # the log-boundary device_get is where a wedged
                    # collective actually manifests on the host —
                    # deadline it like the dispatch window
                    watchdog.arm(iter_num)
                with tracer.span("block", what="log_metrics"):
                    # THE deliberate log-boundary sync, amortized by
                    # log_interval — one batched device_get instead of
                    # the two separate blocking float() fetches this
                    # block used to do (graftlint GL202 found both)
                    loss_f, lr_f = (
                        float(v) for v in jax.device_get(  # graftlint: disable=GL202 (log-boundary sync)
                            (metrics["loss"], metrics["learning_rate"])
                        )
                    )
                    if guard_on:
                        skipped = int(metrics["skipped"])  # graftlint: disable=GL202 (rides the log sync)
                        extra["skipped_steps"] = skipped
                        extra["rollbacks"] = rollbacks
                        if skipped > obs_prev_skipped:
                            obs_anomaly_counter.inc(
                                skipped - obs_prev_skipped, kind="skip"
                            )
                        # after a rollback the in-state total rewinds;
                        # re-base so replayed skips count as new events
                        obs_prev_skipped = skipped
                        # host-side `rollbacks` is monotone by
                        # construction, so set() cannot decrease it
                        obs_anomaly_counter.set(rollbacks, kind="rollback")
                if watchdog is not None:
                    watchdog.disarm()
                n = max(obs_acc_n, 1)
                extra["step_time_ms"] = round(1e3 * obs_acc_step / n, 3)
                extra["data_wait_frac"] = round(
                    obs_acc_data / max(obs_acc_step, 1e-9), 4
                )
                obs_stall_gauge.set(extra["data_wait_frac"])
                if cfg.ckpt_interval > 0:
                    # checkpoint health rides the same records: blocked
                    # time since the last log (back-pressure; ~0 when
                    # the disk keeps up) and the last completed save's
                    # duration, wherever it ran
                    extra["ckpt_blocked_ms"] = round(
                        1e3 * ckpt_acc_blocked, 3
                    )
                    last_save_s = (
                        ckpt_writer.last_save_s
                        if ckpt_writer is not None else ckpt_last_save_s
                    )
                    if last_save_s is not None:
                        extra["ckpt_save_ms"] = round(1e3 * last_save_s, 3)
                    ckpt_acc_blocked = 0.0
                compiles = _compile_entries()
                if compiles is not None:
                    obs_compile_counter.set(compiles)
                    extra["compile_events"] = compiles
                mem = device_memory_mb()  # one query: gauge + record
                if mem is not None:
                    obs_mem_gauge.set_max(mem)
                obs_acc_step = obs_acc_data = 0.0
                obs_acc_n = 0
                logger.log_step(
                    iter_num, loss_f, lr_f,
                    tokens_per_sec=throughput.update(tokens_seen),
                    extra=extra, gpu_memory_mb=mem,
                )

            if iter_num % cfg.eval_interval == 0:
                with tracer.span("eval", iter=iter_num):
                    losses = estimate_loss(
                        eval_many, state["params"], train_ds, val_ds, cfg,
                        eval_rng, materialize=_materialize,
                    )
                logger.log_eval(iter_num, losses["train"], losses["val"])
                if param_summary is not None:
                    # the lambda-evolution + per-group-norm record (see
                    # obs/introspect.py): control contributes norms only,
                    # diff one lambda per layer, ndiff one per term per
                    # layer — the acceptance contract
                    with tracer.span("block", what="introspection"):
                        # deliberate sync at eval cadence (the eval
                        # above already forced one)
                        summ = jax.device_get(param_summary(state["params"]))  # graftlint: disable=GL202 (eval cadence)
                        gnorm = (
                            None if metrics is None
                            else jax.device_get(  # graftlint: disable=GL202 (eval cadence)
                                metrics.get("grad_norm_groups")
                            )
                        )
                    logger.log_record({
                        "record": "introspection", "iter": iter_num,
                        **lambda_record(summ, model_cfg, grad_norms=gnorm),
                    })
                if losses["val"] < best_val_loss:  # train.py:307-317
                    best_val_loss = losses["val"]
                    if is_primary():
                        print(f"Saving best model with val loss: {best_val_loss:.4f}")
                    # Throttle the expensive best-state disk write: it
                    # costs ~3 min at recipe scale on this image's
                    # tunneled chip (device->host measured 5-7 MB/s,
                    # BASELINE.md round 4), and early training improves on
                    # EVERY eval. checkpoint_min_interval_s = 0 (default)
                    # keeps the reference's write-every-improvement
                    # behavior (train.py:307-317) with no extra copy.
                    # When a write is DEFERRED, the best state is
                    # snapshotted on-device instead (an HBM copy — note it
                    # pins a second full train state until flushed; memory-
                    # tight configs should keep the throttle at 0) and any
                    # pending snapshot is flushed at exit, so the final
                    # best.ckpt is identical under any throttle. The
                    # decision must AGREE across ranks (save_checkpoint is
                    # a collective): rank 0's clock decides.
                    write_now = (
                        time.monotonic() - last_best_write
                        >= cfg.checkpoint_min_interval_s
                    )
                    if process_count() > 1:
                        from jax.experimental import multihost_utils

                        flags = multihost_utils.process_allgather(
                            np.float32(1.0 if write_now else 0.0)
                        )
                        write_now = bool(np.asarray(flags).ravel()[0] > 0)
                    if write_now:
                        # collective host-gather inside; the primary writes
                        save_checkpoint(
                            cfg.checkpoint_path, state, best_val_loss, cfg,
                            tokenizer_fingerprint=tok_fp,
                            consumed_windows=consumed_at(iter_num),
                        )
                        best_snapshot = None
                        last_best_write = time.monotonic()
                    else:
                        best_snapshot = jax.tree_util.tree_map(
                            jnp.copy, state
                        )
                        best_snapshot_iter = iter_num

        dt = time.time() - t0
        if dt > 0:
            print(f"Training done: {tokens_seen} tokens in {dt:.1f}s "
                  f"({tokens_seen / dt:.0f} tokens/sec)")
    except BaseException:
        crashed = True
        raise
    finally:
        # these closes must not derail the rescue logic below, and above
        # all must not derail it ASYMMETRICALLY across ranks (a flush
        # error on one host only), so they are contained here
        def _stop_metrics_server():
            if metrics_server is not None:
                metrics_server.shutdown()
                metrics_server.server_close()

        def _close_tracer():
            tracer.close()
            if tracer.path:
                print(f"[obs] span trace written to {tracer.path}")

        def _drain_ckpt_writer():
            # drain the async writer BEFORE the rescue save below: an
            # in-flight step snapshot finishes (and certifies) rather
            # than being abandoned half-written; a job error stored in
            # the writer surfaces here and is printed, not raised
            if ckpt_writer is not None:
                ckpt_writer.close(timeout=600.0)

        def _drain_device_prof():
            # finish the queued device-profile parse (its record must
            # land in metrics.jsonl before logger.finish closes it) and
            # stop any capture window a crashed step left open
            if device_prof is not None:
                device_prof.close()

        def _close_resilience():
            # stop the watchdog monitor FIRST — the rescue save below
            # is a legitimately slow section and must not be deadlined
            # — then the heartbeat threads (peers see this process's
            # silence only after its heartbeat_timeout_s, by which
            # time a clean exit has already torn the job down)
            if watchdog is not None:
                watchdog.close()
            if heartbeat is not None:
                heartbeat.close()

        for closer in (_close_resilience, _drain_device_prof,
                       _drain_ckpt_writer,
                       profiler.close, logger.finish,
                       _close_tracer, _stop_metrics_server):
            try:
                closer()
            except Exception as e:  # noqa: BLE001
                print(f"shutdown cleanup failed (continuing): {e!r}")
        # On MULTI-process runs the rescue save embeds a collective
        # (gather_to_host); if this process is unwinding an exception the
        # other ranks may be anywhere (still in a train_step psum, or
        # crashed differently), and issuing a mismatched collective here
        # would turn one rank's crash into a fleet-wide hang. Skip the
        # rescue on that path — jax's coordination service tears the job
        # down when this process exits, and the last periodic
        # best-checkpoint remains. Deliberately NOT agreed via an
        # OR-reduce across ranks: that agreement would itself be a
        # collective issued from an asymmetric path (peers of a mid-loop
        # crash are still inside train_step psums, not here), i.e. the
        # exact hazard being avoided. Normal completion and the SIGTERM
        # graceful stop exit the loop in lockstep on every rank
        # (_agreed_stop), so their collective save is safe.
        # Single-process keeps the save on every exit path, crashes
        # included.
        skip_collective_rescue = crashed and process_count() > 1
        try:
            try:
                if ckpt_writer is not None and not ckpt_writer.drained:
                    # Drain-ordering invariant: the rescue save below
                    # must never interleave with an in-flight async
                    # periodic save (same rotation tree, racing GC).
                    # The closer above normally drained the writer; if
                    # that drain FAILED (stuck disk, timeout), retry
                    # here — and if it still will not drain, skip the
                    # rescue rather than interleave two writers
                    # (tests/test_ckpt.py pins the ordering with
                    # ckpt_hang).
                    try:
                        ckpt_writer.close(timeout=600.0)
                    except Exception as e:  # noqa: BLE001
                        print(f"checkpoint writer would not drain; "
                              f"skipping rescue save (the certified "
                              f"step tree remains): {e!r}")
                        last_ckpt_path = None
                if last_ckpt_path and not skip_collective_rescue:
                    # resumable last-state checkpoint, written whatever the
                    # exit path (save_checkpoint canonicalizes pipeline
                    # layouts; every process participates in its collective
                    # gather, the primary writes). The SIGTERM handler is
                    # still ours here, so a follow-up SIGTERM during this
                    # save cannot kill the write; the atomic rename inside
                    # save_checkpoint protects against harder kills.
                    finite = True
                    if metrics is not None:
                        # a NaN/diverged state must not overwrite the
                        # previous good rescue checkpoint — save-exceptions
                        # were already caught, but bad VALUES were not
                        finite = bool(
                            np.isfinite(float(jax.device_get(metrics["loss"])))
                        )
                    if finite:
                        save_checkpoint(
                            last_ckpt_path, state, best_val_loss, cfg,
                            tokenizer_fingerprint=tok_fp,
                            consumed_windows=consumed_at(iter_num),
                        )
                    elif is_primary():
                        print(
                            f"skipping last-checkpoint rescue save: "
                            f"non-finite loss at iter {iter_num} (previous "
                            f"checkpoint at {last_ckpt_path!r} left intact)"
                        )
            except Exception as e:  # noqa: BLE001
                # on the crash path the state itself may be poisoned
                # (device OOM) — never let the rescue save mask the real
                # exception
                print(f"last-checkpoint save failed: {e!r}")
            try:
                if best_snapshot is not None and not skip_collective_rescue:
                    # flush the throttled best-state snapshot AFTER the
                    # resumable rescue save above — under a bounded
                    # preemption grace window the last-ckpt (what resume
                    # needs) must land first; the best flush is the
                    # nice-to-have. On the multi-process CRASH path this
                    # (like the rescue save) is skipped — a deferred
                    # improvement is then lost and best.ckpt stays at the
                    # last written state; that is the throttle's one
                    # divergence from write-every-improvement (the
                    # collective gather cannot run from an asymmetric
                    # crash, see skip_collective_rescue above).
                    if is_primary():
                        print(
                            f"writing pending best checkpoint "
                            f"(val loss {best_val_loss:.4f})"
                        )
                    save_checkpoint(
                        cfg.checkpoint_path, best_snapshot, best_val_loss,
                        cfg, tokenizer_fingerprint=tok_fp,
                        consumed_windows=consumed_at(best_snapshot_iter),
                    )
                    best_snapshot = None
            except Exception as e:  # noqa: BLE001
                print(f"pending best-checkpoint save failed: {e!r}")
        finally:
            # restore the caller's SIGTERM handler on EVERY exit path —
            # including a KeyboardInterrupt mid-rescue-save (BaseException
            # escapes the inner except-Exception blocks)
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
    if cfg.mesh.pipeline > 1:
        # return the canonical list-of-blocks layout, like every other
        # path, so callers (tools/ppl_gap.py-style eval, model_forward)
        # work regardless of the training topology
        from differential_transformer_replication_tpu.train.checkpoint import (
            canonicalize_state,
        )

        state = canonicalize_state(
            gather_to_host(state),
            cfg.resolved_model().n_layer,
        )
    return state
