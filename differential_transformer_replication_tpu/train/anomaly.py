"""In-loop anomaly guard: skip bad updates inside the jitted step, roll
back to an in-HBM snapshot when badness persists.

Long runs die to single bad batches far more often than to hard faults:
one non-finite loss poisons the params, and every later step trains a
corpse until a human notices (the reference has no defense at all; its
only health signal is the loss print, train.py:288). Production stacks
(MegaScale, Jiang et al., 2024) treat this as a first-class subsystem.
Three layers here, cheapest first:

1. **Skip** (this module, traced into the step): the step computes a
   ``bad`` flag — non-finite loss/grad-norm, or grad-norm above
   ``anomaly_spike_factor`` × a running EMA of good-step grad norms —
   and applies the optimizer update under ``lax.cond``, so a bad batch
   leaves params, optimizer moments and the EMA untouched. Both branches
   live in ONE compiled program: skipping adds zero recompiles (pinned
   by tests/test_faults.py). The step counter still advances, so the lr
   schedule and the epoch-sampler fast-forward (trainer.py) stay exact.
2. **Rollback** (trainer host loop): the trainer keeps a periodic
   on-device snapshot of a known-good state; when ``bad_streak`` reaches
   ``anomaly_rollback_after`` — skipping didn't cure it, so the state
   itself is suspect (corrupt params, poisoned moments) — it restores
   the snapshot and rewinds the epoch sampler to match, i.e. an in-HBM
   resume without touching disk.
3. **Abort** (trainer): after ``anomaly_max_rollbacks`` rollbacks the
   run raises :class:`TrainingDivergedError`; the trainer's finite-check
   rescue save then refuses to overwrite the last good checkpoint with
   the diverged state (trainer.py finally block).

Multi-process agreement: the guarded step runs under GSPMD jit
(parallel/dp_step.py), where the loss and global grad norm are already
globally reduced values — the partitioner inserts the psums for the
batch-sharded mean — so every rank computes the IDENTICAL ``bad`` flag
and takes the same ``lax.cond`` branch by construction. Collectives
stay matched with no extra communication; the host-side rollback
decision reads a replicated scalar, so it also agrees without a
collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class TrainingDivergedError(RuntimeError):
    """Raised when the rollback budget is exhausted: the run cannot make
    progress and must stop before corrupting its checkpoints."""


def init_guard_state() -> dict:
    """Guard state carried inside the train state (replicated scalars on
    sharded meshes — parallel/sharding.py falls through to P() for them).
    NOT checkpointed: train/checkpoint.py strips it on save and re-seeds
    it on load, so the on-disk format is unchanged and guarded/unguarded
    checkpoints interchange freely (the EMA re-warms after resume)."""
    return {
        # running EMA of grad norms over GOOD steps only (a spike must
        # not raise its own threshold)
        "ema": jnp.zeros((), jnp.float32),
        # good updates applied so far; spike detection stays off until
        # anomaly_warmup_steps of them have seeded the EMA
        "good_steps": jnp.zeros((), jnp.int32),
        # consecutive bad (skipped) steps — the trainer's rollback trigger
        "bad_streak": jnp.zeros((), jnp.int32),
        # total skipped steps this run (monotone; logged via metrics)
        "skipped": jnp.zeros((), jnp.int32),
    }


def apply_guard(cfg, guard: dict, loss, grad_norm, do_update, params,
                opt_state):
    """The traced guard: decide ``bad``, gate the update, advance the
    guard state. ``do_update: () -> (params, opt_state)`` runs the
    optimizer (tx.update + apply_updates) and executes ONLY on good
    steps — a skipped step pays the forward/backward it already ran,
    nothing more. Returns (params, opt_state, guard, extra_metrics)."""
    finite = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
    # at least one good step must have SEEDED the EMA before spike
    # detection can arm — comparing against the zero-init EMA
    # (warmup_steps=0) would flag every step bad forever
    warmed = guard["good_steps"] >= max(cfg.anomaly_warmup_steps, 1)
    spike = warmed & (
        grad_norm > cfg.anomaly_spike_factor * guard["ema"]
    )
    bad = ~finite | spike

    new_params, new_opt_state = jax.lax.cond(
        bad, lambda: (params, opt_state), do_update
    )

    # EMA over good steps; the first good step seeds it directly so the
    # warmup threshold reflects real norms, not a decay from zero
    beta = jnp.float32(cfg.anomaly_ema_beta)
    seeded = jnp.where(
        guard["good_steps"] == 0,
        grad_norm,
        beta * guard["ema"] + (1.0 - beta) * grad_norm,
    )
    new_guard = {
        "ema": jnp.where(bad, guard["ema"], seeded),
        "good_steps": guard["good_steps"] + jnp.where(bad, 0, 1),
        "bad_streak": jnp.where(bad, guard["bad_streak"] + 1, 0),
        "skipped": guard["skipped"] + bad.astype(jnp.int32),
    }
    extra = {
        "bad": bad.astype(jnp.int32),
        "bad_streak": new_guard["bad_streak"],
        "skipped": new_guard["skipped"],
    }
    return new_params, new_opt_state, new_guard, extra


def snapshot_state(state: dict) -> dict:
    """Deep on-device copy of a train state (sharding-preserving). Needed
    both for taking the good-state snapshot and for restoring from it:
    the jitted step DONATES its input state, so the snapshot and the live
    state must never share buffers."""
    return jax.tree_util.tree_map(jnp.copy, state)
