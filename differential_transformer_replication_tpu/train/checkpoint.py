"""Checkpointing and resume.

Two formats, generalizing the reference's pair (SURVEY.md section 3.5):

1. **Training checkpoint** — params + optimizer state + step +
   best_val_loss + full train config (the reference's best-model blob,
   train.py:310-317), PLUS actual resume support, which the reference
   never built (no load path exists in its train.py).
2. **``save_pretrained`` / ``from_pretrained``** — self-describing
   {model_args, model_state} for ALL THREE model families, generalizing
   the N-diff-only implementation (Ndiff_transformer.py:243-265).

Serialization is flax msgpack (pytree-shaped, framework-native) in a
checkpoint directory: ``state.msgpack`` + ``meta.json``.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional, Tuple

import jax
from flax import serialization

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.models import init_model


def save_checkpoint(
    path: str, state: dict, best_val_loss: float, cfg: TrainConfig
) -> None:
    """train.py:310-317 equivalent (model+optimizer+scheduler state; the
    schedule is stateless here, so `step` covers it)."""
    os.makedirs(path, exist_ok=True)
    state = jax.device_get(state)
    meta = {
        "best_val_loss": float(best_val_loss),
        "iter_num": int(state["step"]),
        "config": cfg.to_dict(),
    }
    # Write-then-rename so a crash mid-save (preemption) never destroys the
    # previous good checkpoint.
    _atomic_write(os.path.join(path, "state.msgpack"), serialization.to_bytes(state))
    _atomic_write(
        os.path.join(path, "meta.json"), json.dumps(meta, indent=1).encode()
    )


def _atomic_write(dest: str, data: bytes) -> None:
    tmp = dest + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dest)


def load_checkpoint(path: str, cfg: TrainConfig, target_state: dict) -> Tuple[dict, float]:
    """Restore (state, best_val_loss). ``target_state`` supplies the pytree
    structure (create_train_state output)."""
    if not os.path.isfile(os.path.join(path, "state.msgpack")):
        raise FileNotFoundError(
            f"no checkpoint at {path!r} (expected {path}/state.msgpack)"
        )
    with open(os.path.join(path, "state.msgpack"), "rb") as f:
        state = serialization.from_bytes(target_state, f.read())
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    return state, meta["best_val_loss"]


def save_pretrained(path: str, params: dict, model_cfg: ModelConfig) -> None:
    """Self-describing model checkpoint (Ndiff_transformer.py:251-265),
    for any of the three families."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "params.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(params)))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_args": dataclasses.asdict(model_cfg)}, f, indent=1)


def from_pretrained(path: str) -> Tuple[dict, ModelConfig]:
    """Rebuild config + params (Ndiff_transformer.py:243-249)."""
    with open(os.path.join(path, "config.json")) as f:
        model_cfg = ModelConfig(**json.load(f)["model_args"])
    target = init_model(jax.random.PRNGKey(0), model_cfg)
    with open(os.path.join(path, "params.msgpack"), "rb") as f:
        params = serialization.from_bytes(target, f.read())
    return params, model_cfg
