"""Checkpointing and resume.

Two formats, generalizing the reference's pair (SURVEY.md section 3.5):

1. **Training checkpoint** — params + optimizer state + step +
   best_val_loss + full train config (the reference's best-model blob,
   train.py:310-317), PLUS actual resume support, which the reference
   never built (no load path exists in its train.py).
2. **``save_pretrained`` / ``from_pretrained``** — self-describing
   {model_args, model_state} for ALL THREE model families, generalizing
   the N-diff-only implementation (Ndiff_transformer.py:243-265).

Serialization is flax msgpack (pytree-shaped, framework-native) in a
checkpoint directory: ``state.msgpack`` + ``meta.json`` +
``manifest.json`` (per-file SHA-256 integrity manifest, written LAST —
its presence certifies the checkpoint; train/ckpt_writer.py holds the
durability machinery: atomic fsynced writes, verification, ``step-*``
rotation with retention GC, and the async writer thread).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple

import jax
from flax import serialization

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.models import common, init_model
from differential_transformer_replication_tpu.utils import faults
from differential_transformer_replication_tpu.train.ckpt_writer import (
    AsyncCheckpointWriter,
    CheckpointError,
    atomic_write,
    gc_step_checkpoints,
    list_step_checkpoints,
    read_manifest,
    step_dir_name,
    verify_checkpoint,
    write_manifest,
)

__all__ = [
    "AsyncCheckpointWriter",
    "CheckpointError",
    "ElasticResumeError",
    "canonicalize_state",
    "elastic_resume_info",
    "from_pretrained",
    "load_checkpoint",
    "load_params_for_inference",
    "read_meta",
    "resolve_resume_auto",
    "save_checkpoint",
    "save_pretrained",
    "save_step_checkpoint",
    "verify_checkpoint",
]


class ElasticResumeError(RuntimeError):
    """A checkpoint cannot be resumed onto THIS runtime configuration:
    the model's parameter shapes differ (resharding host state cannot
    invent or drop weights), or the sampler's position cannot be
    reproduced exactly under the new batch math (and
    ``--allow-inexact-resume`` was not given). Always says which field
    diverged and what would make the resume legal — the alternative is
    a deep flax shape error or, worse, a silently wrong data order."""

# legacy alias: the atomic write grew directory fsyncs and fault points
# and moved to ckpt_writer.py, where the jax-free tools can reach it
_atomic_write = atomic_write


def _map_blocks(tree, fn):
    """Apply ``fn`` to every subtree stored under a ``"blocks"`` key,
    anywhere in the state pytree — params AND the optimizer moments that
    mirror them (optax namedtuple states are rebuilt field-wise)."""
    if isinstance(tree, dict):
        return {
            k: (fn(v) if k == "blocks" else _map_blocks(v, fn))
            for k, v in tree.items()
        }
    if isinstance(tree, tuple):
        vals = [_map_blocks(v, fn) for v in tree]
        if hasattr(tree, "_fields"):  # namedtuple (optax states)
            return type(tree)(*vals)
        return tuple(vals)
    if isinstance(tree, list):
        return [_map_blocks(v, fn) for v in tree]
    return tree


def _is_stacked(state: dict) -> bool:
    """Pipeline runs keep ``blocks`` as ONE dict of layer-stacked arrays
    (parallel/pipeline.py:stack_blocks); the canonical layout is a list of
    per-layer dicts."""
    return isinstance(state["params"]["blocks"], dict)


def canonicalize_state(state: dict, n_layer: int) -> dict:
    """Stage-stacked -> canonical list-of-blocks throughout the state
    (params and mirrored optimizer moments), so the on-disk format — and
    ``train()``'s return value — is one layout regardless of which
    parallelism trained it (sample.py and cross-topology resume depend on
    this). Layout transforms live in models/common.py."""
    return _map_blocks(
        state, lambda blocks: common.unstack_block_tree(blocks, n_layer)
    )


def _stack(state: dict) -> dict:
    """Canonical list-of-blocks -> stage-stacked (inverse of
    :func:`canonicalize_state`), applied after loading into a pipeline run."""
    import numpy as np

    return _map_blocks(
        state, lambda blocks: common.stack_block_list(blocks, stack_fn=np.stack)
    )


def save_checkpoint(
    path: str, state: dict, best_val_loss: float, cfg: TrainConfig,
    tokenizer_fingerprint: str | None = None,
    consumed_windows: Optional[int] = None,
) -> None:
    """train.py:310-317 equivalent (model+optimizer+scheduler state; the
    schedule is stateless here, so `step` covers it). Always written in
    the canonical list-of-blocks layout.

    Multi-process safe: EVERY process must call this (the host gather is
    a collective over non-addressable shards, parallel/multihost.py);
    only the primary touches the filesystem. On pods the checkpoint path
    must therefore live on storage every rank can read (NFS/GCS-style
    shared mount) for a later resume — load_checkpoint reads the file on
    every rank, the standard multi-host checkpointing contract."""
    from differential_transformer_replication_tpu.parallel.multihost import (
        gather_to_host,
        is_primary,
    )

    state = gather_to_host(state)
    if not is_primary():
        return
    state = _host_checkpoint_state(state, cfg)
    _write_checkpoint_dir(
        path, state, _checkpoint_meta(state, best_val_loss, cfg,
                                      tokenizer_fingerprint,
                                      consumed_windows)
    )


def _host_checkpoint_state(state: dict, cfg: TrainConfig) -> dict:
    """Host-gathered state -> the canonical on-disk pytree: the
    anomaly-guard scalars (train/anomaly.py) are run-local health state,
    not model state — stripped so the format is identical with the
    guard on or off (load_checkpoint re-seeds a fresh guard from the
    target) — and pipeline stage-stacked layouts are canonicalized."""
    state = {k: v for k, v in state.items() if k != "guard"}
    if _is_stacked(state):
        state = canonicalize_state(state, cfg.resolved_model().n_layer)
    return state


def _checkpoint_meta(
    state: dict, best_val_loss: float, cfg: TrainConfig,
    tokenizer_fingerprint: Optional[str],
    consumed_windows: Optional[int] = None,
) -> dict:
    meta = {
        "best_val_loss": float(best_val_loss),
        "iter_num": int(state["step"]),
        "config": cfg.to_dict(),
        # the epoch sampler's exact position, in WINDOWS CONSUMED —
        # the elastic-resume anchor: a resumed run with a different
        # global batch size fast-forwards the permutation from this
        # count, not from step arithmetic under the new batch math
        # (elastic_resume_info). The trainer supplies the precise
        # value (it may itself have resumed elastically, so step *
        # batch under cfg is not always right); the derivation below
        # covers direct save_checkpoint callers.
        "consumed_windows": int(
            consumed_windows if consumed_windows is not None
            else int(state["step"]) * cfg.grad_acc_steps
            * cfg.micro_batch_size
        ),
    }
    if tokenizer_fingerprint:
        # lets downstream tools (sample.py, tools/attn_probe.py) verify
        # tokenizer CONTENT, not just vocab size (data/tokenizer.py)
        meta["tokenizer_fingerprint"] = tokenizer_fingerprint
    return meta


def _write_checkpoint_dir(path: str, state: dict, meta: dict) -> None:
    """Serialize + write one certified checkpoint directory. Every file
    lands atomically (write temp, fsync, rename, fsync dir —
    ckpt_writer.atomic_write) so a crash mid-save never destroys a
    previous good checkpoint; the integrity manifest goes LAST so an
    interrupted save leaves an UNcertified dir that verification-aware
    readers (load_checkpoint, latest resolution, --resume-from auto)
    skip. Runs on the async writer thread for periodic step
    checkpoints, inline for best/last saves."""
    os.makedirs(path, exist_ok=True)
    atomic_write(
        os.path.join(path, "state.msgpack"), serialization.to_bytes(state)
    )
    atomic_write(
        os.path.join(path, "meta.json"), json.dumps(meta, indent=1).encode()
    )
    write_manifest(
        path, step=meta["iter_num"], config_hash=_config_hash(meta)
    )


def _config_hash(meta: dict) -> Optional[str]:
    """Same recipe hash as train/metrics.py:config_hash (the meta's
    ``config`` IS cfg.to_dict()), recorded in the manifest so two
    checkpoint trees are attributable to the same experiment without
    deserializing anything."""
    cfg = meta.get("config")
    if not isinstance(cfg, dict):
        return None
    import hashlib

    blob = json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def save_step_checkpoint(
    root: str,
    state: dict,
    best_val_loss: float,
    cfg: TrainConfig,
    tokenizer_fingerprint: Optional[str] = None,
    writer: Optional[AsyncCheckpointWriter] = None,
    keep_last: int = 3,
    keep_every: int = 0,
    consumed_windows: Optional[int] = None,
) -> float:
    """One rotating periodic checkpoint: ``<root>/step-NNNNNNNN``,
    certified by its manifest, followed by retention GC (keep the
    newest ``keep_last`` verified + every ``keep_every``-th step).

    Multi-process safe like :func:`save_checkpoint`: EVERY process must
    call it (the host gather is a collective); only the primary touches
    the filesystem. With a ``writer`` the caller thread pays only the
    device->host snapshot (the gather) — serialization, file I/O,
    certification and GC run on the writer thread, and the return value
    is the back-pressure wall time spent waiting for a still-in-flight
    previous save (0.0 when idle, or always in sync mode)."""
    from differential_transformer_replication_tpu.parallel.multihost import (
        gather_to_host,
        is_primary,
    )

    state = gather_to_host(state)  # collective; host-resident numpy out
    if not is_primary():
        return 0.0
    state = _host_checkpoint_state(state, cfg)
    path = os.path.join(root, step_dir_name(int(state["step"])))
    meta = _checkpoint_meta(state, best_val_loss, cfg,
                            tokenizer_fingerprint, consumed_windows)

    def job() -> None:
        # chaos stall point (utils/faults.py "ckpt_hang"): a slow disk.
        # Runs INSIDE the job so an async save stalls on the writer
        # thread — the loop must keep stepping and the next submit must
        # exercise back-pressure (tests/test_ckpt.py)
        faults.stall("ckpt_hang")
        _write_checkpoint_dir(path, state, meta)
        gc_step_checkpoints(root, keep_last=keep_last, keep_every=keep_every)

    if writer is None:
        job()
        return 0.0
    return writer.submit(job)


# model-config fields that DETERMINE parameter shapes: a checkpoint
# whose saved values differ here cannot be resharded onto the runtime
# (host state would have to invent or drop weights); everything else
# (impl selectors, dtypes-in-compute, dropout) is resume-compatible
_SHAPE_FIELDS = (
    "model", "n_embd", "n_head", "n_layer", "block_size", "n_terms",
)


def elastic_resume_info(meta: dict, cfg: TrainConfig) -> dict:
    """Validate checkpoint-vs-runtime compatibility for a (possibly
    elastic) resume and return the facts the trainer needs.

    Checkpoints are stored host-canonical (unsharded, list-of-blocks),
    so a resume onto a *different* mesh shape — the normal outcome of
    a Cloud-TPU preemption returning fewer devices — is legal whenever
    the parameter shapes match: ``shard_state`` simply reshards the
    host pytree onto the new mesh, optimizer moments included. That
    used to work by accident; this makes it an explicit, tested
    contract:

    - **shape compatibility is asserted** field-by-field
      (:data:`_SHAPE_FIELDS` + vocab_size + control_head_multiplier),
      raising :class:`ElasticResumeError` naming every divergent field
      instead of a deep flax deserialization error,
    - **the sampler anchor is re-derived from consumed windows**, not
      step count: the meta's recorded ``consumed_windows`` (or, for
      older checkpoints, step x the SAVING run's batch math) keeps the
      epoch permutation exact when the new global batch size differs,
    - **inexactness is typed**: when the consumed count is not a
      multiple of the new global batch (the optimizer-step boundary
      and the data position can no longer coincide — a
      mid-accumulation boundary) or a legacy checkpoint predates the
      recorded count while the batch math changed, the resume raises
      unless ``cfg.allow_inexact_resume`` accepts the drift.

    Returns ``{"elastic", "batch_changed", "exact", "saved_mesh",
    "consumed_windows"}`` (``consumed_windows`` is None only for a
    legacy checkpoint with an unchanged batch — derive with the
    current math)."""
    saved_cfg = meta.get("config") or {}
    saved_model = saved_cfg.get("model") or {}

    new_model = cfg.model
    mismatches = []
    for f in _SHAPE_FIELDS:
        if f in saved_model and saved_model[f] != getattr(new_model, f):
            mismatches.append(
                f"model.{f}: checkpoint {saved_model[f]!r} vs runtime "
                f"{getattr(new_model, f)!r}"
            )
    for f in ("vocab_size", "control_head_multiplier"):
        if f in saved_cfg and saved_cfg[f] != getattr(cfg, f):
            mismatches.append(
                f"{f}: checkpoint {saved_cfg[f]!r} vs runtime "
                f"{getattr(cfg, f)!r}"
            )
    if mismatches:
        raise ElasticResumeError(
            "checkpoint parameter shapes are incompatible with this "
            "run — elastic resume reshards, it cannot reshape: "
            + "; ".join(mismatches)
            + ". Match the model config, or start fresh."
        )

    saved_mesh = saved_cfg.get("mesh") or {}
    new_mesh = dataclasses.asdict(cfg.mesh)
    elastic = bool(saved_mesh) and saved_mesh != new_mesh

    consumed = meta.get("consumed_windows")
    saved_batch = None
    if "grad_acc_steps" in saved_cfg and "micro_batch_size" in saved_cfg:
        saved_batch = (
            int(saved_cfg["grad_acc_steps"])
            * int(saved_cfg["micro_batch_size"])
        )
        if consumed is None and "iter_num" in meta:
            # pre-consumed_windows checkpoint: the SAVING run's batch
            # math is still recorded in its config — derive exactly
            consumed = int(meta["iter_num"]) * saved_batch
    new_batch = cfg.grad_acc_steps * cfg.micro_batch_size
    batch_changed = saved_batch is not None and saved_batch != new_batch

    exact = True
    problem = None
    if consumed is None:
        if batch_changed:
            problem = (
                "the checkpoint records neither consumed_windows nor "
                "its batch math, and the global batch size changed "
                f"(now {new_batch}) — the epoch-sampler position "
                "cannot be reproduced"
            )
    elif int(consumed) % new_batch != 0:
        problem = (
            f"consumed_windows={int(consumed)} is not a multiple of "
            f"the new global batch ({new_batch} windows/step): the "
            "resume lands mid-accumulation, so optimizer steps and "
            "data position cannot stay aligned exactly"
        )
    if problem is not None:
        exact = False
        if not cfg.allow_inexact_resume:
            raise ElasticResumeError(
                f"elastic resume cannot be exact: {problem}. Restore "
                "the original --grad-acc-steps/--micro-batch-size, or "
                "pass --allow-inexact-resume to accept a bounded "
                "sampler drift."
            )
    return {
        "elastic": elastic,
        "batch_changed": batch_changed,
        "exact": exact,
        "saved_mesh": saved_mesh or None,
        "consumed_windows": None if consumed is None else int(consumed),
    }


def resolve_resume_auto(
    cfg: TrainConfig,
) -> Tuple[Optional[str], List[Tuple[str, str]]]:
    """``--resume-from auto``: the newest checkpoint (by recorded step)
    that PASSES manifest verification, among the run's rotating
    ``step-*`` tree, its rescue last-checkpoint and its best
    checkpoint — falling back to older ones, so a crash mid-save can
    never wedge the restart loop. Returns ``(path_or_None, skipped)``
    where ``skipped`` lists ``(path, reason)`` for every candidate that
    failed a check before the winner was found (fed to the
    ``ckpt_verify_failures`` counter); candidates older than the
    winner are not audited."""
    candidates = [p for _, p in list_step_checkpoints(cfg.resolved_ckpt_dir())]
    for path in (cfg.resolved_last_checkpoint_path(), cfg.checkpoint_path):
        if path and os.path.isdir(path):
            candidates.append(path)
    # order by recorded step from a CHEAP manifest read (no hashing),
    # then verify digests newest-first and stop at the first pass — a
    # large keep_every audit trail must not turn every restart into a
    # full-tree re-hash. Stable sort: at equal steps the step-dir wins
    # over last/best (candidate insertion order).
    ordered: List[Tuple[int, int, str]] = []
    skipped: List[Tuple[str, str]] = []
    for i, path in enumerate(candidates):
        try:
            step = int(read_manifest(path).get("step", -1))
        except CheckpointError as e:
            skipped.append((path, str(e)))
            continue
        ordered.append((step, -i, path))
    for _, _, path in sorted(ordered, reverse=True):
        try:
            verify_checkpoint(path)
            return path, skipped
        except CheckpointError as e:
            skipped.append((path, str(e)))
    return None, skipped


def load_checkpoint(
    path: str, cfg: TrainConfig, target_state: dict, verify: bool = True,
) -> Tuple[dict, float]:
    """Restore (state, best_val_loss). ``target_state`` supplies the pytree
    structure (create_train_state output). A stage-stacked target (pipeline
    run) is transparently loaded from the canonical on-disk layout and
    re-stacked, so checkpoints move freely across parallelism topologies.

    ``verify`` (default on) re-hashes every file against the integrity
    manifest before deserializing: a corrupted or partially-written
    checkpoint raises a :class:`CheckpointError` naming the file and
    the expected/actual digest instead of being silently loaded (a
    bit-flipped optimizer moment trains — wrongly — without it). A
    manifest-less legacy checkpoint also raises; pass ``verify=False``
    to load one anyway (or stamp it with ``tools/ckpt_doctor.py
    --adopt-legacy``)."""
    if not os.path.isfile(os.path.join(path, "state.msgpack")):
        raise FileNotFoundError(
            f"no checkpoint at {path!r} (expected {path}/state.msgpack)"
        )
    if verify:
        verify_checkpoint(path)
    # checkpoints never carry the anomaly-guard scalars (save_checkpoint
    # strips them); a guarded target gets a fresh guard re-attached so
    # the EMA/streak re-warm after resume
    guard = target_state.get("guard")
    target = {k: v for k, v in target_state.items() if k != "guard"}
    stacked = _is_stacked(target)
    if stacked:
        target = canonicalize_state(target, cfg.resolved_model().n_layer)
    state_path = os.path.join(path, "state.msgpack")
    try:
        with open(state_path, "rb") as f:
            state = serialization.from_bytes(target, f.read())
    except Exception as e:
        raise CheckpointError(
            f"cannot deserialize checkpoint state at {state_path!r}: "
            f"{type(e).__name__}: {e}. The file is truncated/corrupt or "
            "from an incompatible model/optimizer config — restore it "
            "from a good copy or resume from a different checkpoint"
        ) from e
    if stacked:
        state = _stack(state)
    if guard is not None:
        state["guard"] = guard
    meta = read_meta(path)
    try:
        best = meta["best_val_loss"]
    except KeyError as e:
        raise CheckpointError(
            f"checkpoint meta at {os.path.join(path, 'meta.json')!r} has "
            "no 'best_val_loss' — the file is corrupt or not a training "
            "checkpoint"
        ) from e
    return state, best


def read_meta(path: str) -> dict:
    """Load and validate a checkpoint dir's meta.json, raising one clear
    :class:`CheckpointError` (naming the path) on truncated/garbage
    content instead of a bare JSONDecodeError."""
    meta_path = os.path.join(path, "meta.json")
    try:
        with open(meta_path) as f:
            return json.load(f)
    except FileNotFoundError:
        raise CheckpointError(
            f"no checkpoint metadata at {meta_path!r} (the directory is "
            "not a checkpoint, or the save was interrupted before the "
            "atomic rename)"
        ) from None
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointError(
            f"cannot parse checkpoint metadata at {meta_path!r}: {e}. "
            "The file is truncated or corrupt — restore it from a good "
            "copy or resume from a different checkpoint"
        ) from e


def load_params_for_inference(
    path: str, verify: bool = True, quantize: Optional[str] = None,
) -> Tuple[dict, ModelConfig, dict]:
    """Load a TRAINING checkpoint dir (meta.json + state.msgpack) for
    inference-only use: returns (params, resolved ModelConfig, meta).

    This is the meta->TrainConfig->create_train_state->load_checkpoint
    dance every inference front-end needs (sample.py, the serving
    server, tools/serve_bench.py) in one place; ``meta`` is the raw
    meta.json dict so callers can check ``tokenizer_fingerprint``
    (data/tokenizer.py:check_tokenizer_matches). For ``save_pretrained``
    dirs use :func:`from_pretrained` instead.

    ``verify`` has :func:`load_checkpoint` semantics: digest-check the
    integrity manifest before serving the weights (corrupt weights in
    production are worse than a startup error); ``verify=False`` is
    the escape hatch for pre-manifest checkpoints (or certify them
    once with ``tools/ckpt_doctor.py --adopt-legacy``).

    ``quantize="int8"`` applies per-channel symmetric int8
    quantize-then-dequantize to every matmul weight on load
    (ops/decode_attention.py:``quantize_params_int8`` — the
    ``--quantize-weights`` flag on sample.py / serving.server);
    embeddings, norms and lambda vectors stay exact. Tolerance-gated
    in tests/test_decode_attention.py."""
    from differential_transformer_replication_tpu.train.step import (
        create_train_state,
    )

    _validate_quantize(quantize)
    meta = read_meta(path)
    try:
        saved = meta["config"]
        cfg = TrainConfig(
            model=ModelConfig(**saved["model"]),
            vocab_size=saved["vocab_size"],
            control_head_multiplier=saved["control_head_multiplier"],
        )
    except (KeyError, TypeError) as e:
        raise CheckpointError(
            f"checkpoint metadata at "
            f"{os.path.join(path, 'meta.json')!r} is missing the saved "
            f"train config ({type(e).__name__}: {e}) — the file is "
            "corrupt or from an incompatible version"
        ) from e
    # abstract target: only the pytree STRUCTURE matters to from_bytes,
    # so skip materializing a random-init model + two Adam moment trees
    # (~3x the params in transient memory at serving startup) that the
    # deserialized buffers would immediately replace
    target = jax.eval_shape(
        lambda: create_train_state(jax.random.PRNGKey(0), cfg)
    )
    state, _ = load_checkpoint(path, cfg, target, verify=verify)
    params = apply_weight_quantization(state["params"], quantize)
    return params, cfg.resolved_model(), meta


def _validate_quantize(quantize: Optional[str]) -> None:
    if quantize not in ("int8", None, "", "none"):
        raise ValueError(
            f"unsupported weight quantization {quantize!r}; expected "
            "'int8' or None"
        )


def apply_weight_quantization(params: dict, quantize: Optional[str]) -> dict:
    """The one place the ``--quantize-weights`` option is interpreted:
    validates ``quantize`` and returns ``params`` with per-channel int8
    quantize-then-dequantize applied to every matmul weight (or
    untouched for None/""/"none"). Shared by every inference load path
    — :func:`load_params_for_inference`, :func:`from_pretrained`, and
    the serving server's random-init demo model."""
    _validate_quantize(quantize)
    if quantize == "int8":
        from differential_transformer_replication_tpu.ops.decode_attention import (
            quantize_params_int8,
        )

        params = quantize_params_int8(params)
    return params


def save_pretrained(path: str, params: dict, model_cfg: ModelConfig) -> None:
    """Self-describing model checkpoint (Ndiff_transformer.py:251-265),
    for any of the three families."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "params.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(params)))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_args": dataclasses.asdict(model_cfg)}, f, indent=1)


def from_pretrained(
    path: str, quantize: Optional[str] = None,
) -> Tuple[dict, ModelConfig]:
    """Rebuild config + params (Ndiff_transformer.py:243-249).

    ``quantize`` has :func:`load_params_for_inference` semantics
    (:func:`apply_weight_quantization`)."""
    with open(os.path.join(path, "config.json")) as f:
        model_cfg = ModelConfig(**json.load(f)["model_args"])
    target = init_model(jax.random.PRNGKey(0), model_cfg)
    with open(os.path.join(path, "params.msgpack"), "rb") as f:
        params = serialization.from_bytes(target, f.read())
    return apply_weight_quantization(params, quantize), model_cfg
