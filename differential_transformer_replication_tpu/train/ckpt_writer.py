"""Durable-checkpoint machinery: integrity manifests, atomic+fsynced
writes, `step-NNNNNNNN` rotation with retention GC, and the background
writer thread that keeps checkpoint I/O off the train loop.

Durability model (CheckFreq/Gemini-style, PAPERS.md):

- **Atomicity**: every file lands via :func:`atomic_write` — temp file,
  fsync, rename over the destination, fsync of the parent directory
  (without the last step the *rename itself* can be lost on power
  failure even though both file contents survived).
- **Certification**: a checkpoint directory is trustworthy iff its
  ``manifest.json`` verifies — per-file SHA-256 + byte sizes, plus the
  step and config hash. The manifest is written LAST, so a crash at any
  earlier point leaves a directory that :func:`verify_checkpoint`
  rejects and ``latest``-resolution skips. Loaders re-hash before
  deserializing, so a corrupted or partially-written checkpoint is
  never silently loaded.
- **Rotation**: periodic snapshots live in ``<root>/step-NNNNNNNN``
  directories. :func:`gc_step_checkpoints` keeps the newest
  ``keep_last`` verified checkpoints (plus every ``keep_every``-th
  step forever) and deletes the rest manifest-FIRST — the inverse of
  the write order, so a crash mid-delete leaves an unverified (hence
  skipped) directory, never a verified-but-truncated one.
- **Async**: :class:`AsyncCheckpointWriter` runs serialization + file
  I/O on a daemon thread; the train loop blocks only for the
  device->host snapshot. One save may be in flight at a time — a
  submit while one is running blocks (back-pressure) and reports the
  blocked wall time for the ``ckpt_blocked`` telemetry.

This module imports only the stdlib at module scope, so
``tools/train_supervisor.py`` and ``tools/ckpt_doctor.py`` can load it
by file path and verify checkpoints without dragging in jax (the
supervisor must stay alive when the runtime it babysits is the thing
crashing). Fault points (utils/faults.py: ``ckpt_write``,
``ckpt_fsync``, ``ckpt_manifest``, ``ckpt_gc``, ``ckpt_hang``) are
resolved lazily and are inert when the faults module is unavailable.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import re
import shutil
import sys
import threading
import time
from typing import Callable, List, Optional, Tuple

MANIFEST_NAME = "manifest.json"
MANIFEST_FORMAT = 1
_STEP_DIR_RE = re.compile(r"^step-(\d+)$")


class CheckpointError(RuntimeError):
    """A checkpoint on disk cannot be trusted or read: truncated/corrupt
    file, failed digest verification, or a layout from an incompatible
    run. Always names the offending path — the actionable signal (delete,
    repair, or re-point) a deep msgpack/KeyError traceback buries."""


def _faults():
    """The process-wide fault-injection plan (utils/faults.py), resolved
    lazily so this module stays importable (by file path, no package)
    in jax-free processes; None = injection unavailable -> inert."""
    mod = sys.modules.get(
        "differential_transformer_replication_tpu.utils.faults"
    )
    if mod is not None:
        return mod
    try:
        from differential_transformer_replication_tpu.utils import faults
        return faults
    except Exception:  # spec-loaded standalone without the package
        return None


def _fault_check(point: str) -> None:
    f = _faults()
    if f is not None:
        f.check(point)


def _fault_stall(point: str) -> None:
    f = _faults()
    if f is not None and hasattr(f, "stall"):
        f.stall(point)


# -- atomic + durable file I/O --------------------------------------------


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY: makes renames/unlinks inside it durable. A
    rename is only crash-safe once the directory entry itself is on
    disk — fsyncing the file is not enough. Best-effort on platforms
    without directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write(dest: str, data: bytes) -> None:
    """Durable atomic replace: write ``dest + ".tmp"``, fsync the file,
    rename over ``dest``, fsync the parent directory. A crash at ANY
    point leaves either the old content or the new content at ``dest``,
    never a mixture — and once this returns, the new content survives
    power loss.

    Fault points: ``ckpt_write`` fires between the temp fsync and the
    rename (temp fully written, destination untouched); ``ckpt_fsync``
    fires between the rename and the directory fsync (the window where
    a power cut can roll the rename back)."""
    tmp = dest + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        _fault_check("ckpt_write")
        os.replace(tmp, dest)
        _fault_check("ckpt_fsync")
        fsync_dir(os.path.dirname(dest) or ".")
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def file_sha256(path: str, chunk_size: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk_size), b""):
            h.update(block)
    return h.hexdigest()


# -- integrity manifest ---------------------------------------------------


def write_manifest(
    path: str, step: int, config_hash: Optional[str] = None
) -> dict:
    """Hash every regular file in the checkpoint dir and write
    ``manifest.json`` LAST (atomic + fsynced), certifying the
    checkpoint: its presence + passing digests are what
    :func:`verify_checkpoint` trusts. Fault point ``ckpt_manifest``
    fires just before the write — a crash there leaves a complete but
    UNcertified directory, exactly what latest-resolution must skip."""
    files = {}
    for name in sorted(os.listdir(path)):
        fp = os.path.join(path, name)
        if name == MANIFEST_NAME or name.endswith(".tmp"):
            continue
        if not os.path.isfile(fp):
            continue
        files[name] = {
            "sha256": file_sha256(fp),
            "bytes": os.path.getsize(fp),
        }
    manifest = {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "files": files,
        "written_at": round(time.time(), 3),
    }
    if config_hash:
        manifest["config_hash"] = config_hash
    _fault_check("ckpt_manifest")
    atomic_write(
        os.path.join(path, MANIFEST_NAME),
        json.dumps(manifest, indent=1, sort_keys=True).encode(),
    )
    return manifest


def read_manifest(path: str) -> dict:
    """The dir's manifest, or a :class:`CheckpointError` naming the path
    when it is missing (uncertified: the save was interrupted before
    certification, or predates integrity manifests) or unparseable."""
    mp = os.path.join(path, MANIFEST_NAME)
    try:
        with open(mp, "rb") as f:
            manifest = json.loads(f.read().decode())
    except FileNotFoundError:
        raise CheckpointError(
            f"no integrity manifest at {mp!r} — the checkpoint is "
            "uncertified (the save was interrupted before the manifest "
            "write, or it predates integrity manifests; "
            "tools/ckpt_doctor.py --adopt-legacy can stamp one)"
        ) from None
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointError(
            f"cannot parse integrity manifest at {mp!r}: {e}. The file "
            "is truncated or corrupt — the checkpoint cannot be trusted"
        ) from e
    if not isinstance(manifest, dict) or not isinstance(
        manifest.get("files"), dict
    ):
        raise CheckpointError(
            f"integrity manifest at {mp!r} has no 'files' table — the "
            "file is corrupt or not a checkpoint manifest"
        )
    return manifest


def verify_checkpoint(path: str) -> dict:
    """Re-hash every manifest-listed file and compare sizes + SHA-256
    digests. Returns the manifest on success; raises
    :class:`CheckpointError` naming the first offending file and the
    expected/actual digest on any mismatch."""
    if not os.path.isdir(path):
        raise CheckpointError(f"no checkpoint directory at {path!r}")
    manifest = read_manifest(path)
    for name, rec in sorted(manifest["files"].items()):
        fp = os.path.join(path, name)
        if not os.path.isfile(fp):
            raise CheckpointError(
                f"checkpoint file {fp!r} is listed in the manifest but "
                "missing on disk — the checkpoint is incomplete"
            )
        size = os.path.getsize(fp)
        want_size = rec.get("bytes")
        if want_size is not None and size != want_size:
            raise CheckpointError(
                f"checkpoint file {fp!r} is {size} bytes, manifest "
                f"expects {want_size} — the file is truncated or was "
                "rewritten outside a certified save"
            )
        digest = file_sha256(fp)
        if digest != rec.get("sha256"):
            raise CheckpointError(
                f"checkpoint file {fp!r} fails integrity verification: "
                f"expected sha256 {rec.get('sha256')}, got {digest} — "
                "the file is corrupt; resume from a different checkpoint "
                "or repair with tools/ckpt_doctor.py"
            )
    return manifest


def is_verified(path: str) -> bool:
    """Whether the directory holds a certified, digest-clean checkpoint
    (the no-raise form of :func:`verify_checkpoint`)."""
    try:
        verify_checkpoint(path)
        return True
    except CheckpointError:
        return False


def is_certified(path: str) -> bool:
    """Whether the directory carries a parseable manifest — the save
    COMPLETED — without re-hashing its contents. Retention decisions
    key on this (cheap: one small json read per dir, not a full-tree
    digest pass on every periodic save); digest-level trust is checked
    where it matters, at resume/load/doctor time."""
    try:
        read_manifest(path)
        return True
    except CheckpointError:
        return False


# -- step rotation + latest resolution ------------------------------------


def step_dir_name(step: int) -> str:
    return f"step-{int(step):08d}"


def parse_step_dir(name: str) -> Optional[int]:
    m = _STEP_DIR_RE.match(name)
    return int(m.group(1)) if m else None


def list_step_checkpoints(root: str) -> List[Tuple[int, str]]:
    """(step, path) for every ``step-*`` directory under root,
    ascending by step — verified or not."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        step = parse_step_dir(name)
        path = os.path.join(root, name)
        if step is not None and os.path.isdir(path):
            out.append((step, path))
    return sorted(out)


def latest_verified_checkpoint(
    root: str,
) -> Tuple[Optional[str], List[Tuple[str, str]]]:
    """The newest ``step-*`` checkpoint under ``root`` that passes
    manifest verification, falling back to older ones — so a crash
    mid-save (which leaves the newest dir uncertified) can never wedge
    a restart. Returns ``(path_or_None, skipped)`` where ``skipped``
    lists ``(path, reason)`` for every newer dir that failed."""
    skipped: List[Tuple[str, str]] = []
    for step, path in reversed(list_step_checkpoints(root)):
        try:
            verify_checkpoint(path)
            return path, skipped
        except CheckpointError as e:
            skipped.append((path, str(e)))
    return None, skipped


# -- retention GC ---------------------------------------------------------


def delete_checkpoint_dir(path: str) -> None:
    """Crash-safe checkpoint deletion: the manifest goes FIRST (and the
    removal is made durable with a directory fsync), atomically turning
    the dir into an uncertified one that every reader already skips;
    only then are the data files and the directory removed. The inverse
    of the write order — no crash point leaves a certified directory
    with missing or partial data. Fault point ``ckpt_gc`` fires in the
    window between de-certification and data deletion."""
    manifest = os.path.join(path, MANIFEST_NAME)
    try:
        os.unlink(manifest)
    except FileNotFoundError:
        pass
    fsync_dir(path)
    _fault_check("ckpt_gc")
    shutil.rmtree(path, ignore_errors=True)
    parent = os.path.dirname(path)
    if parent:
        fsync_dir(parent)


def gc_step_checkpoints(
    root: str, keep_last: int, keep_every: int = 0
) -> Tuple[List[str], List[str]]:
    """Retention policy over the ``step-*`` tree: keep the newest
    ``keep_last`` CERTIFIED checkpoints (manifest present — see
    :func:`is_certified`; GC is retention, not a digest audit), plus
    every checkpoint whose step is a multiple of ``keep_every`` (0 =
    none); delete the rest — including uncertified leftovers from
    crashed saves. Single-writer: the caller (the async writer thread,
    or an operator running ckpt_doctor on an idle tree) must be the
    only process mutating ``root``. Returns ``(kept, deleted)``
    paths."""
    entries = list_step_checkpoints(root)
    certified = [(s, p) for s, p in entries if is_certified(p)]
    keep = {p for _, p in certified[-keep_last:]} if keep_last > 0 else set()
    if keep_every > 0:
        keep |= {p for s, p in certified if s % keep_every == 0}
    kept, deleted = [], []
    for _, path in entries:
        if path in keep:
            kept.append(path)
        else:
            delete_checkpoint_dir(path)
            deleted.append(path)
    return kept, deleted


# -- the async writer -----------------------------------------------------


class AsyncCheckpointWriter:
    """One daemon thread that runs checkpoint save jobs (serialize +
    write + certify + GC) off the train loop.

    Contract: at most ONE save is in flight. :meth:`submit` hands the
    job over immediately when the writer is idle; while a save is still
    running it BLOCKS (back-pressure — checkpoints must not silently
    pile up host-RAM snapshots faster than the disk drains them) and
    returns the blocked wall-clock seconds so the caller can feed its
    ``ckpt_blocked`` histogram. A job that raises does not kill the
    thread: the first error is stored and re-raised from the next
    :meth:`submit` or :meth:`close` on the caller's thread, where the
    trainer can decide whether a failed periodic save is fatal.

    The caller must hand jobs that close over HOST data only (the
    device->host snapshot happens on the submitting thread) — each
    pending job pins one host-RAM copy of the state until written.
    """

    def __init__(self, save_hist=None, blocked_hist=None) -> None:
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._idle = threading.Event()
        self._idle.set()
        self._error: Optional[BaseException] = None
        self._error_lock = threading.Lock()
        self._save_hist = save_hist
        self._blocked_hist = blocked_hist
        self.last_save_s: Optional[float] = None
        self.saves_completed = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    @property
    def drained(self) -> bool:
        """Whether the writer is closed AND its thread has exited —
        i.e. no save can still be touching the checkpoint tree. The
        trainer's rescue save asserts this before writing (a rescue
        interleaving with an in-flight periodic save would race its
        retention GC)."""
        return self._closed and not self._thread.is_alive()

    def _raise_pending(self) -> None:
        with self._error_lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def submit(self, job: Callable[[], None]) -> float:
        """Enqueue one save job; returns seconds spent blocked waiting
        for a still-in-flight previous save (0.0 when idle). A PRIOR
        job's stored error is re-raised — but only after THIS job is
        enqueued, so one transient disk failure loses exactly the save
        that failed, never also the healthy snapshot that follows it."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointWriter is closed")
        t0 = time.perf_counter()
        self._idle.wait()
        blocked = time.perf_counter() - t0
        if self._blocked_hist is not None:
            self._blocked_hist.observe(blocked)
        self._idle.clear()
        self._q.put(job)
        self._raise_pending()
        return blocked

    def _loop(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            t0 = time.perf_counter()
            try:
                job()
            except BaseException as e:  # noqa: BLE001 — surfaced on submit/close
                with self._error_lock:
                    if self._error is None:
                        self._error = e
            else:
                # success-only bookkeeping: a failed job must not show
                # up as a healthy save duration in the telemetry
                dt = time.perf_counter() - t0
                self.last_save_s = dt
                self.saves_completed += 1
                if self._save_hist is not None:
                    self._save_hist.observe(dt)
            finally:
                # drop the closure BEFORE blocking on the next get():
                # it pins the multi-GB host snapshot it closed over,
                # which must be freed when the save lands, not held for
                # the whole next ckpt_interval window
                job = None
                self._idle.set()

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain: finish any in-flight/queued save, stop the thread,
        re-raise the first stored job error. Called from the trainer's
        exit path so a graceful stop never abandons a half-queued
        snapshot."""
        if not self._closed:
            self._closed = True
            self._q.put(None)
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise RuntimeError(
                "checkpoint writer thread did not drain within "
                f"{timeout}s (a save is stuck in file I/O)"
            )
        self._raise_pending()
