"""The jitted training and evaluation steps.

Replaces the reference's eager hot loop (train.py:261-283) with a single
compiled XLA program per optimizer step: forward, backward, clip, AdamW
update, and (when grad_acc_steps > 1) a ``lax.scan`` over microbatches —
the counter-based Python accumulation at train.py:265-283 becomes part of
the compiled step.

The train state is a plain pytree dict so sharding specs apply uniformly:
``{"params": ..., "opt_state": ..., "step": ...}``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax

from differential_transformer_replication_tpu.config import ModelConfig, TrainConfig
from differential_transformer_replication_tpu.models import init_model, model_forward
from differential_transformer_replication_tpu.train.anomaly import (
    apply_guard,
    init_guard_state,
)
from differential_transformer_replication_tpu.train.optim import make_optimizer


def create_train_state(key: jax.Array, cfg: TrainConfig) -> dict:
    model_cfg = cfg.resolved_model()
    params = init_model(key, model_cfg)
    tx, _ = make_optimizer(cfg)
    state = {
        "params": params,
        "opt_state": tx.init(params),
        "step": jnp.zeros((), jnp.int32),
    }
    if cfg.anomaly_guard:
        # guard scalars ride inside the state so the skip/streak logic is
        # part of the one compiled step; checkpointing strips them
        # (train/checkpoint.py), keeping the on-disk format unchanged
        state["guard"] = init_guard_state()
    return state


def loss_fn(
    params: dict,
    x: jnp.ndarray,
    y: jnp.ndarray,
    model_cfg: ModelConfig,
    rng: Optional[jax.Array] = None,
    mesh=None,
) -> jnp.ndarray:
    _, loss = model_forward(params, x, model_cfg, targets=y, rng=rng, mesh=mesh)
    return loss


def make_step_fn(cfg: TrainConfig, mesh=None, param_sync=None,
                 loss_sync=None, grad_sync=None):
    """The raw (un-jitted) optimizer-step function — reused by the
    single-device jit below and by the sharded jit in parallel/dp_step.py
    (which passes its Mesh so attention can go sequence-parallel).

    ``batch`` is ``{"x": (A, B, T), "y": (A, B, T)}`` with A =
    grad_acc_steps microbatches (A=1 for the reference default,
    train.py:68). Gradients are averaged over microbatches, matching the
    reference's ``loss / grad_acc_steps`` scaling (train.py:265).

    ``param_sync``/``loss_sync`` are the overlap-scheduled DP hooks
    (parallel/dp_step.py): ``param_sync`` is an identity-forward pytree
    transform applied to the params INSIDE the differentiated loss, so
    its custom-VJP backward (a per-bucket ``lax.pmean``) fires the
    gradient all-reduce for each layer group as soon as that group's
    cotangents exist — overlapped with the rest of backward instead of
    exposed after it. ``loss_sync`` maps the shard-local loss to the
    global mean for metrics and the anomaly guard. ``grad_sync``
    directly pmeans a gradient pytree; when grad_acc_steps > 1 the
    microbatch scan uses the LOCAL loss and applies it ONCE to the
    accumulated grads — baking param_sync into the scanned loss would
    fire the full per-bucket all-reduce set every microbatch (A x the
    collective volume for a numerically identical result, pmean being
    linear). All three default to None (single-device / GSPMD
    placement, where the partitioner inserts the collectives).
    """
    model_cfg = cfg.resolved_model()
    tx, schedule = make_optimizer(cfg)
    if param_sync is None:
        _loss = loss_fn
    else:
        def _loss(params, x, y, model_cfg, r, mesh):
            return loss_fn(param_sync(params), x, y, model_cfg, r, mesh)

    # the accumulation scan differentiates the LOCAL loss when grad_sync
    # handles the post-scan sync (module docstring)
    _scan_loss = loss_fn if grad_sync is not None else _loss

    def run_grad(params, x, y, r, scale, lf=_loss):
        """value_and_grad of ``lf``, optionally loss-scaled: ``scale`` is
        the fault-injection poison (utils/faults.py) — NaN there makes the
        loss AND every gradient NaN, the exact failure the anomaly guard
        must catch. None (no fault armed) is the production path."""
        if scale is None:
            return jax.value_and_grad(lf)(params, x, y, model_cfg, r, mesh)
        return jax.value_and_grad(
            lambda p: lf(p, x, y, model_cfg, r, mesh) * scale
        )(params)

    def step(state: dict, batch: dict, rng: Optional[jax.Array] = None):
        n_micro = batch["x"].shape[0]
        # (A,) poison scales, present ONLY when NaN faults are armed (the
        # trainer then includes it in EVERY batch so the pytree structure
        # — and the compiled program — never changes mid-run)
        poison = batch.get("poison")
        if n_micro == 1:
            # the reference default (grad_acc_steps=1, train.py:68): skip
            # the scan entirely — the zero-init + accumulate + loop
            # slice/carry machinery costs ~5% of the step at recipe scale
            # (measured via profile; the adds alone pass over all 94M
            # params) for a one-iteration loop
            r = None if rng is None else jax.random.fold_in(rng, 0)
            loss, grads = run_grad(
                state["params"], batch["x"][0], batch["y"][0], r,
                None if poison is None else poison[0],
            )
        else:
            def micro(carry, xs):
                grads_acc, loss_acc, i = carry
                if poison is None:
                    x, y = xs
                    sc = None
                else:
                    x, y, sc = xs
                r = None if rng is None else jax.random.fold_in(rng, i)
                loss, grads = run_grad(state["params"], x, y, r, sc,
                                       lf=_scan_loss)
                grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
                return (grads_acc, loss_acc + loss, i + 1), None

            xs = (batch["x"], batch["y"])
            if poison is not None:
                xs = xs + (poison,)
            zeros = jax.tree_util.tree_map(jnp.zeros_like, state["params"])
            (grads, loss_sum, _), _ = jax.lax.scan(
                micro, (zeros, jnp.zeros(()), jnp.zeros((), jnp.int32)),
                xs,
            )
            grads = jax.tree_util.tree_map(lambda g: g / n_micro, grads)
            loss = loss_sum / n_micro
            if grad_sync is not None:
                # one full-gradient all-reduce per STEP; pmean-of-mean ==
                # mean-of-per-microbatch-pmeans, at 1/A the traffic
                grads = grad_sync(grads)

        if loss_sync is not None:
            # shard-local -> global mean loss, BEFORE the guard reads it:
            # every shard must judge the same scalar or lax.cond could
            # take different branches per device (grads are already
            # globally synced by param_sync's backward)
            loss = loss_sync(loss)
        grad_norm = optax.global_norm(grads)
        # per-layer-group gradient norms ((L+2,): embed, blocks, head) —
        # the observability layer logs them next to the per-layer lambdas
        # every eval interval (obs/introspect.py). A handful of reduces
        # over already-materialized grads; the vector stays on device
        # unless the trainer actually fetches it.
        from differential_transformer_replication_tpu.obs.introspect import (
            group_norms,
        )

        gg = group_norms(grads)
        metrics = {
            "loss": loss,
            "learning_rate": schedule(state["step"]),
            "grad_norm": grad_norm,
            "grad_norm_groups": jnp.concatenate([
                gg["embed"][None], gg["blocks"], gg["head"][None]
            ]),
        }

        def do_update():
            updates, opt_state = tx.update(
                grads, state["opt_state"], state["params"]
            )
            return optax.apply_updates(state["params"], updates), opt_state

        if cfg.anomaly_guard:
            # skip the update on a bad step under lax.cond — one compiled
            # program either way (compile count pinned, tests/test_faults
            # .py); the step counter still advances so the lr schedule
            # and the epoch-sampler fast-forward stay exact
            params, opt_state, guard, extra = apply_guard(
                cfg, state["guard"], loss, grad_norm, do_update,
                state["params"], state["opt_state"],
            )
            metrics.update(extra)
        else:
            params, opt_state = do_update()

        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        if cfg.anomaly_guard:
            new_state["guard"] = guard
        return new_state, metrics

    return step


def make_train_step(cfg: TrainConfig):
    """``step(state, batch, rng) -> (state, metrics)``, jitted for the
    default (single-device) placement. The state is donated — same
    throughput on v5e (XLA already aliases most buffers) but roughly
    halves peak HBM across the update, like the sharded path
    (parallel/dp_step.py)."""
    return jax.jit(make_step_fn(cfg), donate_argnums=(0,))


def make_multi_train_step(cfg: TrainConfig, steps_per_call: int):
    """``multi(state, batches, rngs) -> (state, stacked metrics)``: K
    optimizer steps per jitted call via ``lax.scan``.

    Why this exists: every program launch marshals each train-state leaf
    (params + two Adam moments per param, ~470 buffers at the reference
    recipe) through the PJRT layer on BOTH sides of the call — measured
    ~5 ms/launch on this platform against an ~81 ms busy step, i.e. ~6%
    of the whole step wasted on argument bookkeeping. Scanning K steps
    inside one program pays that cost once per K steps. The inner math
    is exactly :func:`make_step_fn`, so K=1 and K>1 runs are
    numerically identical given identical batch/rng sequences.

    ``batches``: ``{"x": (K, A, B, T), "y": ...}``; ``rngs``: stacked
    (K, ...) dropout keys, or None when dropout is off (the trainer
    folds one key per global iteration either way, so resume at any
    K-boundary reproduces the same mask sequence)."""
    step = make_step_fn(cfg)
    use_dropout = cfg.resolved_model().dropout > 0.0

    @partial(jax.jit, donate_argnums=(0,))
    def multi(state: dict, batches: dict, rngs=None):
        assert batches["x"].shape[0] == steps_per_call, (
            f"batches carry {batches['x'].shape[0]} steps, expected "
            f"{steps_per_call} (shape (K, A, B, T))"
        )

        def body(st, xs):
            if use_dropout:
                x, y, r = xs
            else:
                x, y = xs
                r = None
            return step(st, {"x": x, "y": y}, r)

        xs = (batches["x"], batches["y"])
        if use_dropout:
            xs = xs + (rngs,)
        return jax.lax.scan(body, state, xs)

    return multi


def make_eval_step(cfg: TrainConfig, mesh=None):
    """Returns ``eval_step(params, x, y) -> loss``, jitted; dropout off
    (model.eval() semantics, train.py:128). Pass the training mesh so a
    sequence-parallel run also evaluates through the ring path instead of
    all-gathering the sequence."""
    model_cfg = cfg.resolved_model()

    @jax.jit
    def eval_step(params: dict, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
        return loss_fn(params, x, y, model_cfg, rng=None, mesh=mesh)

    return eval_step


def make_eval_many(cfg: TrainConfig, mesh=None):
    """Returns ``eval_many(params, xs, ys) -> (K,) losses``: a single
    jitted ``lax.scan`` over K stacked eval batches, so an eval pass does
    ONE device->host sync instead of one per batch (the reference's
    estimate_loss loop syncs 400 times per eval, train.py:125-139). The
    per-batch math is identical to :func:`make_eval_step`."""
    model_cfg = cfg.resolved_model()

    @jax.jit
    def eval_many(params: dict, xs: jnp.ndarray, ys: jnp.ndarray) -> jnp.ndarray:
        def body(_, xy):
            x, y = xy
            return None, loss_fn(params, x, y, model_cfg, rng=None, mesh=mesh)

        _, losses = jax.lax.scan(body, None, (xs, ys))
        return losses

    return eval_many
