from differential_transformer_replication_tpu.train.anomaly import (
    TrainingDivergedError,
    init_guard_state,
)
from differential_transformer_replication_tpu.train.optim import (
    cosine_warmup_schedule,
    make_optimizer,
)
from differential_transformer_replication_tpu.train.step import (
    create_train_state,
    make_eval_many,
    make_eval_step,
    make_multi_train_step,
    make_train_step,
)
from differential_transformer_replication_tpu.train.checkpoint import (
    AsyncCheckpointWriter,
    CheckpointError,
    ElasticResumeError,
    elastic_resume_info,
    from_pretrained,
    load_checkpoint,
    resolve_resume_auto,
    save_checkpoint,
    save_pretrained,
    save_step_checkpoint,
    verify_checkpoint,
)
from differential_transformer_replication_tpu.train.watchdog import (
    HANG_EXIT_CODE,
    StepWatchdog,
)
from differential_transformer_replication_tpu.train.metrics import MetricLogger
from differential_transformer_replication_tpu.train.trainer import (
    build_data,
    estimate_loss,
    train,
)

__all__ = [
    "TrainingDivergedError",
    "init_guard_state",
    "CheckpointError",
    "ElasticResumeError",
    "elastic_resume_info",
    "HANG_EXIT_CODE",
    "StepWatchdog",
    "cosine_warmup_schedule",
    "make_optimizer",
    "create_train_state",
    "make_eval_many",
    "make_eval_step",
    "make_multi_train_step",
    "make_train_step",
    "AsyncCheckpointWriter",
    "save_checkpoint",
    "save_step_checkpoint",
    "load_checkpoint",
    "resolve_resume_auto",
    "verify_checkpoint",
    "save_pretrained",
    "from_pretrained",
    "MetricLogger",
    "train",
    "build_data",
    "estimate_loss",
]
