"""Step-deadline watchdog: no training iteration may hang silently.

On a real pod the dominant training failure is not a crash the
supervisor (tools/train_supervisor.py) can see — it is a *wedge*: one
host dies or stalls and every other host blocks forever inside a
``psum``, burning the whole slice with zero signal. This is the
trainer analogue of the serving engine's ``step_time_budget_s``
watchdog (serving/server.py), with one crucial difference: a serving
iteration that blows its budget is merely flagged degraded, but a
training iteration that blows its deadline is **unrecoverable from
inside the process** (the device call cannot be interrupted), so the
watchdog converts the silent hang into a *supervised restart*:

1. dump a ``hang_report.json`` — every thread's stack, the current
   iteration, the compile counter, the last ``device_profile`` row,
   whatever context callables the trainer wired in — so the wedge is
   debuggable post-mortem,
2. emit one ``{"record": "hang"}`` metrics row and bump
   ``train_watchdog_fires_total``,
3. ``os._exit`` with :data:`HANG_EXIT_CODE`, a code
   ``tools/train_supervisor.py:classify_exit`` maps to the ``hang``
   outcome (restartable, budgeted separately from ``crash``).

``os._exit`` (not ``sys.exit``) is deliberate: the main thread is
wedged inside a device call, so no Python-level unwinding can run —
the rescue-save machinery would itself hang. The step-checkpoint tree
plus ``--resume-from auto`` is the recovery path, exactly like a
SIGKILL.

The watchdog is also the **coordinated-abort** sink for the multi-host
liveness mesh (parallel/heartbeat.py): a peer silent past its
heartbeat timeout calls :meth:`StepWatchdog.trip`, which fires
immediately — armed or not — converting "wait out the collective
forever" into "restart within seconds".

Module scope imports only the stdlib (the ckpt_writer.py convention):
everything jax-flavored reaches the report through injected context
callables, and the clock / exit function are injectable so tier-1
tests exercise every path without killing the test process.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

# Exit status of a watchdog fire. Distinct from every code the trainer
# can exit with organically (0, 1, tracebacks) and outside the shell's
# 128+signal band, so the supervisor can classify it unambiguously as
# ``hang``. Mirrored in tools/train_supervisor.py (which must not
# import this package — keep the two in sync).
HANG_EXIT_CODE = 113


def thread_stacks() -> Dict[str, str]:
    """Formatted stack of every live thread, keyed by thread name —
    the first thing a hang post-mortem needs (WHERE is the main thread
    blocked: a psum, a device_get, a disk write?)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, str] = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        out[name] = "".join(traceback.format_stack(frame))
    return out


def dump_hang_report(
    path: str,
    iter_num: Optional[int],
    reason: str,
    budget_s: float,
    context: Optional[Dict[str, Callable[[], object]]] = None,
) -> dict:
    """Write the hang post-mortem JSON (best-effort atomic: temp +
    rename; a watchdog firing must never die half-way through its own
    diagnostics). Context callables are evaluated here, each guarded —
    a broken introspection hook must not eat the report."""
    report: dict = {
        "record": "hang",
        "ts": round(time.time(), 3),
        "iter": iter_num,
        "reason": reason,
        "budget_s": budget_s,
        "pid": os.getpid(),
        "threads": thread_stacks(),
    }
    for key, fn in (context or {}).items():
        try:
            report[key] = fn()
        except Exception as e:  # noqa: BLE001 — diagnostics stay best-effort
            report[key] = f"<context error: {e!r}>"
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        print(f"[watchdog] could not write hang report to {path!r}: {e!r}",
              file=sys.stderr)
    return report


class StepWatchdog:
    """Deadline monitor for the train loop's armed sections.

    Contract: the trainer calls :meth:`arm` with the current iteration
    before each section that must make progress (the jitted-step
    dispatch plus the host syncs that follow it, the log-boundary
    fetch) and :meth:`disarm` after — legitimately long sections
    (eval, checkpoint writes) run disarmed. A monitor thread fires
    when an armed deadline expires; :meth:`trip` fires immediately
    from any thread regardless of arming (the heartbeat mesh's
    coordinated abort).

    ``budget_s <= 0`` disables the deadline monitor (no thread) but
    keeps :meth:`trip` live, so a heartbeat-only configuration still
    has an abort path. All fire paths converge on ``_fire``, which
    runs at most once per process.

    Injectables — ``clock`` (monotonic seconds), ``exit_fn`` (defaults
    to ``os._exit``), ``sink`` (metrics-row callable), ``fires_counter``
    (``.inc()``-able) — exist so tests can drive expiry with a fake
    clock and observe the fire instead of dying from it.
    """

    def __init__(
        self,
        budget_s: float,
        report_path: Optional[str] = None,
        sink: Optional[Callable[[dict], None]] = None,
        fires_counter=None,
        context: Optional[Dict[str, Callable[[], object]]] = None,
        clock: Callable[[], float] = time.monotonic,
        exit_fn: Callable[[int], None] = os._exit,
        poll_s: Optional[float] = None,
        report_timeout_s: float = 10.0,
    ) -> None:
        self.budget_s = float(budget_s)
        self.report_path = report_path
        self._sink = sink
        self._fires_counter = fires_counter
        self._context = dict(context or {})
        self._clock = clock
        self._exit_fn = exit_fn
        self._report_timeout_s = float(report_timeout_s)
        self._lock = threading.Lock()
        self._armed = False
        self._deadline = 0.0
        self._iter: Optional[int] = None
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.budget_s > 0:
            self._poll_s = (
                float(poll_s) if poll_s is not None
                else min(max(self.budget_s / 4.0, 0.01), 0.25)
            )
            self._thread = threading.Thread(
                target=self._monitor, name="train-watchdog", daemon=True
            )
            self._thread.start()

    @property
    def fired(self) -> bool:
        with self._lock:
            return self._fired

    def add_context(self, **fns: Callable[[], object]) -> None:
        """Register more report-time context callables (the trainer
        wires these up as the subsystems they introspect come to
        exist: compile counter, device-profile sampler, heartbeat
        ages)."""
        self._context.update(fns)

    def arm(self, iter_num: int, budget_s: Optional[float] = None) -> None:
        """Start (or refresh) the deadline for one armed section."""
        budget = self.budget_s if budget_s is None else float(budget_s)
        with self._lock:
            self._armed = True
            self._iter = int(iter_num)
            self._deadline = self._clock() + budget

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    def check(self) -> None:
        """Synchronous expiry check (tests; monitor-less budgets)."""
        with self._lock:
            expired = (
                self._armed and not self._fired
                and self._clock() > self._deadline
            )
            iter_num = self._iter
        if expired:
            self._fire(
                f"train step exceeded its {self.budget_s:.1f}s deadline "
                f"at iter {iter_num}", iter_num,
            )

    def trip(self, reason: str) -> None:
        """Immediate fire from any thread, armed or not — the
        heartbeat mesh's coordinated abort: a dead peer means the next
        collective wedges, so waiting for the local deadline only
        burns budget."""
        with self._lock:
            iter_num = self._iter
        self._fire(reason, iter_num)

    def close(self) -> None:
        """Stop the monitor thread (normal trainer shutdown)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- internals ------------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.wait(self._poll_s):
            self.check()

    def _fire(self, reason: str, iter_num: Optional[int]) -> None:
        with self._lock:
            if self._fired:
                return
            self._fired = True
            self._armed = False
        print(f"[watchdog] {reason} — dumping hang report and exiting "
              f"{HANG_EXIT_CODE} for a supervised restart",
              file=sys.stderr, flush=True)
        if self._fires_counter is not None:
            try:
                self._fires_counter.inc()
            except Exception:  # noqa: BLE001
                pass

        def _diagnose() -> None:
            report = (
                dump_hang_report(self.report_path, iter_num, reason,
                                 self.budget_s, self._context)
                if self.report_path else
                {"record": "hang", "ts": round(time.time(), 3),
                 "iter": iter_num, "reason": reason,
                 "budget_s": self.budget_s}
            )
            if self._sink is not None:
                try:
                    # the metrics row carries the summary, not the
                    # stacks (those belong in the report file)
                    self._sink({
                        k: v for k, v in report.items() if k != "threads"
                    })
                except Exception:  # noqa: BLE001
                    pass
            done.set()

        # The diagnostics do blocking I/O — and the likeliest hang on a
        # pod IS stuck shared storage, which is also where the report
        # path usually lives (the checkpoint mount). Writing from the
        # fire thread would wedge the watchdog itself (open/fsync on a
        # hung mount never raises, it blocks), so the report runs on a
        # bounded helper thread: give it report_timeout_s, then exit
        # regardless. Exiting with the hang code is the contract; the
        # post-mortem is best-effort.
        done = threading.Event()
        threading.Thread(target=_diagnose, name="watchdog-report",
                         daemon=True).start()
        if not done.wait(self._report_timeout_s):
            print(f"[watchdog] hang report did not complete within "
                  f"{self._report_timeout_s:.0f}s (diagnostics storage "
                  "is itself stuck?); exiting without it",
                  file=sys.stderr, flush=True)
        self._exit_fn(HANG_EXIT_CODE)
