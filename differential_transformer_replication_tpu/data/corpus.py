"""Corpus loading.

The reference hard-codes HF ``load_dataset('roneneldan/TinyStories')``
(train.py:155). Here the source is a config switch:
  - ``"tinystories"``: the HF dataset if a local cache exists (this
    environment has no network egress; we never download),
  - ``"synthetic"``: a seeded generator of TinyStories-like text so the
    full pipeline runs hermetically,
  - a filesystem path: plain text, one document per line.

Falls back from tinystories to synthetic with a warning rather than
failing, so training is always runnable.
"""

from __future__ import annotations

import os
import sys
from typing import List

_SYNTH_NOUNS = [
    "cat", "dog", "bird", "tree", "ball", "house", "river", "star", "frog",
    "bear", "boat", "cake", "hat", "moon", "sun", "fish", "girl", "boy",
    "dragon", "garden", "mouse", "cloud", "flower", "stone", "fox", "owl",
]
_SYNTH_NAMES = [
    "Tom", "Lily", "Max", "Mia", "Sam", "Anna", "Ben", "Sue", "Tim", "Amy",
    "Leo", "Zoe", "Jack", "Emma", "Finn", "Ruby",
]
_SYNTH_VERBS = [
    "found", "saw", "liked", "chased", "made", "lost", "painted", "carried",
    "hugged", "shared", "hid", "threw", "caught", "visited", "built",
]
_SYNTH_ADJS = [
    "big", "small", "red", "happy", "sad", "shiny", "old", "funny", "brave",
    "tiny", "green", "soft", "loud", "quiet", "kind",
]


def synthetic_corpus(num_docs: int, seed: int = 1337) -> List[str]:
    """Seeded TinyStories-like documents: short simple sentences with a
    tiny vocabulary, enough structure for a small LM to learn from.

    All randomness is drawn in a handful of vectorized numpy calls — the
    original per-sentence ``rng.choice`` loop cost minutes at the
    reference's 1M-document scale (~10M generator calls) and dominated
    pipeline startup."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_sent = rng.integers(2, 6, size=num_docs)
    total = int(n_sent.sum())
    doc_names = rng.choice(_SYNTH_NAMES, size=num_docs)
    nouns = rng.choice(_SYNTH_NOUNS, size=total)
    verbs = rng.choice(_SYNTH_VERBS, size=total)
    adjs = rng.choice(_SYNTH_ADJS, size=total)
    forms = rng.integers(0, 4, size=total)
    others = rng.choice(_SYNTH_NAMES, size=total)

    docs = []
    s = 0
    for i in range(num_docs):
        name = doc_names[i]
        sents = []
        for j in range(s, s + int(n_sent[i])):
            f = forms[j]
            if f == 0:
                sents.append(f"{name} {verbs[j]} a {adjs[j]} {nouns[j]}.")
            elif f == 1:
                sents.append(f"One day, {name} {verbs[j]} the {nouns[j]}.")
            elif f == 2:
                sents.append(f"The {nouns[j]} was very {adjs[j]}.")
            else:
                sents.append(
                    f"{name} and {others[j]} {verbs[j]} a {nouns[j]} together."
                )
        s += int(n_sent[i])
        docs.append(" ".join(sents))
    return docs


def load_corpus(dataset: str, num_train_samples: int, seed: int = 1337) -> List[str]:
    """Returns the first ``num_train_samples`` documents (train.py:165)."""
    return load_corpus_resolved(dataset, num_train_samples, seed)[0]


def load_corpus_resolved(
    dataset: str, num_train_samples: int, seed: int = 1337
) -> tuple:
    """Like ``load_corpus``, but also returns the name of the source
    actually used — callers that cache derived artifacts must key on this,
    not the requested name, or the tinystories->synthetic fallback would
    poison the cache for later online runs."""
    if dataset == "synthetic":
        return synthetic_corpus(num_train_samples, seed), "synthetic"
    if dataset == "tinystories":
        try:
            from datasets import load_dataset

            ds = load_dataset("roneneldan/TinyStories")
            return list(ds["train"]["text"][:num_train_samples]), "tinystories"
        except Exception as e:  # no cache / no network
            print(
                f"[data] TinyStories unavailable ({type(e).__name__}); "
                "falling back to the synthetic corpus",
                file=sys.stderr,
            )
            return synthetic_corpus(num_train_samples, seed), "synthetic"
    if os.path.exists(dataset):
        with open(dataset, "r", encoding="utf-8") as f:
            texts = [line.rstrip("\n") for line in f if line.strip()]
        return texts[:num_train_samples], dataset
    raise ValueError(f"unknown dataset {dataset!r} (not a known name or a path)")
