"""Corpus loading.

The reference hard-codes HF ``load_dataset('roneneldan/TinyStories')``
(train.py:155). Here the source is a config switch:
  - ``"tinystories"``: the HF dataset if a local cache exists (this
    environment has no network egress; we never download),
  - ``"synthetic"``: a seeded generator of TinyStories-like text so the
    full pipeline runs hermetically,
  - a filesystem path: plain text, one document per line.

Falls back from tinystories to synthetic with a warning rather than
failing, so training is always runnable.
"""

from __future__ import annotations

import os
import sys
from typing import List

_SYNTH_NOUNS = [
    "cat", "dog", "bird", "tree", "ball", "house", "river", "star", "frog",
    "bear", "boat", "cake", "hat", "moon", "sun", "fish", "girl", "boy",
    "dragon", "garden", "mouse", "cloud", "flower", "stone", "fox", "owl",
]
_SYNTH_NAMES = [
    "Tom", "Lily", "Max", "Mia", "Sam", "Anna", "Ben", "Sue", "Tim", "Amy",
    "Leo", "Zoe", "Jack", "Emma", "Finn", "Ruby",
]
_SYNTH_VERBS = [
    "found", "saw", "liked", "chased", "made", "lost", "painted", "carried",
    "hugged", "shared", "hid", "threw", "caught", "visited", "built",
]
_SYNTH_ADJS = [
    "big", "small", "red", "happy", "sad", "shiny", "old", "funny", "brave",
    "tiny", "green", "soft", "loud", "quiet", "kind",
]


def synthetic_corpus(num_docs: int, seed: int = 1337) -> List[str]:
    """Seeded TinyStories-like documents: short simple sentences with a
    tiny vocabulary, enough structure for a small LM to learn from."""
    import numpy as np

    rng = np.random.default_rng(seed)
    names = rng.choice(_SYNTH_NAMES, size=num_docs)
    docs = []
    for i in range(num_docs):
        n_sent = int(rng.integers(2, 6))
        name = names[i]
        sents = []
        for _ in range(n_sent):
            noun = rng.choice(_SYNTH_NOUNS)
            verb = rng.choice(_SYNTH_VERBS)
            adj = rng.choice(_SYNTH_ADJS)
            form = int(rng.integers(0, 4))
            if form == 0:
                sents.append(f"{name} {verb} a {adj} {noun}.")
            elif form == 1:
                sents.append(f"One day, {name} {verb} the {noun}.")
            elif form == 2:
                sents.append(f"The {noun} was very {adj}.")
            else:
                other = rng.choice(_SYNTH_NAMES)
                sents.append(f"{name} and {other} {verb} a {noun} together.")
        docs.append(" ".join(sents))
    return docs


def load_corpus(dataset: str, num_train_samples: int, seed: int = 1337) -> List[str]:
    """Returns the first ``num_train_samples`` documents (train.py:165)."""
    if dataset == "synthetic":
        return synthetic_corpus(num_train_samples, seed)
    if dataset == "tinystories":
        try:
            from datasets import load_dataset

            ds = load_dataset("roneneldan/TinyStories")
            return list(ds["train"]["text"][:num_train_samples])
        except Exception as e:  # no cache / no network
            print(
                f"[data] TinyStories unavailable ({type(e).__name__}); "
                "falling back to the synthetic corpus",
                file=sys.stderr,
            )
            return synthetic_corpus(num_train_samples, seed)
    if os.path.exists(dataset):
        with open(dataset, "r", encoding="utf-8") as f:
            texts = [line.rstrip("\n") for line in f if line.strip()]
        return texts[:num_train_samples]
    raise ValueError(f"unknown dataset {dataset!r} (not a known name or a path)")
