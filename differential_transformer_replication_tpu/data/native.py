"""ctypes bridge to the native data-pipeline library (native/src/).

Compiles ``data_native.cpp`` with g++ on first use (cached by source
mtime under ``native/build/``) and exposes:

  - ``permute_indices(n, seed, start, count)`` — a window of the seeded
    O(1)-memory Feistel permutation of [0, n),
  - ``gather_windows(tokens, offsets, block)`` — threaded host-side
    stride-1 window gather (train.py:104-107 semantics).

When no C++ toolchain is available the same Feistel construction runs as
vectorized numpy (bit-identical by design — the tests assert it), so
framework behavior never depends on the native build succeeding.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "native" / "src" / "data_native.cpp"
_BUILD_DIR = _REPO_ROOT / "native" / "build"
_LIB_PATH = _BUILD_DIR / "libdata_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _compile() -> bool:
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # compile to a process-unique temp path and rename atomically: the
    # threading lock is per-process, and concurrent jobs on one checkout
    # must never dlopen a half-written .so
    tmp = _BUILD_DIR / f".libdata_native.{os.getpid()}.so"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        tmp.unlink(missing_ok=True)
        return False


def _load() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and load the shared library; None on failure."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            stale = (
                not _LIB_PATH.exists()
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
            )
            if stale and not _compile():
                _load_failed = True
                return None
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.permute_indices.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_int64),
            ]
            lib.gather_windows.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_uint64,
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
            ]
            _lib = lib
        except OSError:
            _load_failed = True
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# numpy mirror of the C++ Feistel (bit-identical; tests assert parity)
# ---------------------------------------------------------------------------

_U64 = np.uint64


def _mix64(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = (x + _U64(0x9E3779B97F4A7C15)).astype(_U64)
        x = ((x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)).astype(_U64)
        x = ((x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)).astype(_U64)
        return x ^ (x >> _U64(31))


def _feistel_params(n: int):
    bits = 1
    while (1 << bits) < n and bits < 62:
        bits += 1
    half_bits = (bits + 1) // 2
    return half_bits, (1 << half_bits) - 1


def _cipher_np(x: np.ndarray, seed: int, half_bits: int, half_mask: int):
    l = x >> _U64(half_bits)
    r = x & _U64(half_mask)
    for rnd in range(4):
        f = _mix64(r ^ _U64(seed) ^ (_U64(rnd) << _U64(56))) & _U64(half_mask)
        l, r = r, l ^ f
    return (l << _U64(half_bits)) | r


def _permute_np(n: int, seed: int, start: int, count: int) -> np.ndarray:
    seed = int(_mix64(np.array(seed, _U64)))
    half_bits, half_mask = _feistel_params(n)
    x = np.arange(start, start + count, dtype=_U64)
    x = _cipher_np(x, seed, half_bits, half_mask)
    # cycle-walk stragglers back into [0, n)
    out = (x >= _U64(n))
    while out.any():
        x[out] = _cipher_np(x[out], seed, half_bits, half_mask)
        out = (x >= _U64(n))
    return x.astype(np.int64)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def permute_indices(n: int, seed: int, start: int, count: int) -> np.ndarray:
    """``sigma(start : start+count)`` for the seeded permutation sigma of
    [0, n) — the epoch-exact shuffle at O(1) memory (vs the reference
    DataLoader's O(n) randperm, train.py:184-191)."""
    if count <= 0:
        return np.empty((0,), np.int64)
    if start + count > n:
        raise ValueError(f"window [{start}, {start + count}) exceeds domain {n}")
    lib = _load()
    if lib is None:
        return _permute_np(n, seed, start, count)
    out = np.empty(count, np.int64)
    lib.permute_indices(
        n, seed, start, count,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return out


def gather_windows(tokens: np.ndarray, offsets: np.ndarray, block: int) -> dict:
    """Host-side stride-1 window gather: x[b] = tokens[o:o+block],
    y[b] = tokens[o+1:o+block+1] (train.py:104-107). For corpora kept in
    host RAM; the device-resident path is data/sampler.py."""
    tokens = np.ascontiguousarray(tokens, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    if offsets.size and (offsets.min() < 0 or offsets.max() + block + 1 > len(tokens)):
        raise ValueError("offsets out of range for the token stream")
    B = len(offsets)
    lib = _load()
    if lib is None:
        pos = offsets[:, None] + np.arange(block + 1)[None, :]
        grab = tokens[pos]
        return {"x": grab[:, :-1].copy(), "y": grab[:, 1:].copy()}
    x = np.empty((B, block), np.int32)
    y = np.empty((B, block), np.int32)
    lib.gather_windows(
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(tokens),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), B, block,
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    return {"x": x, "y": y}


class EpochPermutation:
    """Exact epoch-shuffle semantics of the reference's shuffled DataLoader
    (train.py:184-191): every window index appears exactly once per epoch,
    a fresh permutation each epoch, O(1) memory. ``take(count)`` streams
    the next ``count`` indices, rolling epochs as needed."""

    def __init__(self, n: int, seed: int):
        if n <= 0:
            raise ValueError("empty index domain")
        self.n = n
        self.seed = seed
        self.epoch = 0
        self.cursor = 0

    def _epoch_seed(self) -> int:
        return int(_mix64(np.array(self.seed, _U64) ^ _U64(self.epoch)))

    def take(self, count: int) -> np.ndarray:
        parts = []
        remaining = count
        while remaining > 0:
            avail = self.n - self.cursor
            grab = min(avail, remaining)
            parts.append(
                permute_indices(self.n, self._epoch_seed(), self.cursor, grab)
            )
            self.cursor += grab
            remaining -= grab
            if self.cursor == self.n:
                self.cursor = 0
                self.epoch += 1
        return np.concatenate(parts) if len(parts) > 1 else parts[0]
