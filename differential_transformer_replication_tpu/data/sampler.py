"""Stride-1 window sampling over the token stream, device-resident.

Replicates the reference's data semantics (train.py:95-107, 178-200):
  - 90/10 contiguous train/val split of the flat token stream,
  - dense stride-1 overlapping windows: window i is
    ``tokens[i : i+block_size]`` with target ``tokens[i+1 : i+block_size+1]``,
  - train batches draw shuffled window offsets; val batches are
    sequential (shuffle=False), drop_last semantics.

TPU re-design: the reference moved the whole corpus to the GPU and
gathered per-item in Python (train.py:97,104-107). Here the token array
lives on device once and a jitted vectorized gather materializes a whole
``(B, T)`` batch from a batch of offsets — no per-item host work, no
host->device copies in the hot loop.

Sampling deviation (documented): the reference's DataLoader shuffles via
a full permutation of ~1e8 window indices per epoch; we draw offsets
uniformly WITH replacement per batch from a seeded numpy Generator. For
stride-1 overlapping windows this is statistically indistinguishable for
training purposes and removes a giant host-side randperm.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def split_tokens(tokens: np.ndarray, val_fraction: float = 0.1) -> Tuple[np.ndarray, np.ndarray]:
    """Contiguous 90/10 split (train.py:178-180)."""
    n = int((1.0 - val_fraction) * len(tokens))
    return tokens[:n], tokens[n:]


@partial(jax.jit, static_argnames=("block_size",))
def _gather_windows(tokens: jnp.ndarray, offsets: jnp.ndarray, block_size: int):
    pos = offsets[:, None] + jnp.arange(block_size + 1)[None, :]
    grab = tokens[pos]  # (B, T+1)
    return {"x": grab[:, :-1], "y": grab[:, 1:]}


class TokenWindows:
    """Device-resident stride-1 window dataset (train.py:95-107)."""

    def __init__(self, tokens: np.ndarray, block_size: int):
        if len(tokens) <= block_size:
            raise ValueError(
                f"need more than block_size={block_size} tokens, got {len(tokens)}"
            )
        self.block_size = block_size
        # host copy kept for the multi-host path (host_batches): each
        # process gathers only its own windows as numpy, then
        # jax.make_array_from_process_local_data assembles the global
        # batch (parallel/multihost.py). Host RAM is cheap; the device
        # copy below is what the hot loop gathers from.
        self._host_tokens = np.asarray(tokens, dtype=np.int32)
        self.tokens = jnp.asarray(tokens, dtype=jnp.int32)

    def __len__(self) -> int:
        """Number of valid windows: len(tokens) - block_size (train.py:102)."""
        return int(self.tokens.shape[0]) - self.block_size

    def batch(self, offsets: np.ndarray) -> dict:
        """Gather x/y windows for explicit offsets. Offsets must be in
        [0, len(self))."""
        return _gather_windows(self.tokens, jnp.asarray(offsets, jnp.int32), self.block_size)

    def random_batch(self, rng: np.random.Generator, batch_size: int) -> dict:
        """Shuffled-loader equivalent (train.py:184-191)."""
        offsets = rng.integers(0, len(self), size=batch_size, dtype=np.int64)
        return self.batch(offsets)

    def sequential_offsets(self, batch_index: int, batch_size: int) -> np.ndarray:
        """Offsets of the unshuffled-loader batch k (train.py:193-200):
        windows [k*B, (k+1)*B), wrapping at the end (drop_last keeps every
        batch full)."""
        if batch_size > len(self):
            # A JAX gather would clamp out-of-range offsets into silently
            # duplicated windows; fail loudly like DataLoader's drop_last
            # yielding nothing.
            raise ValueError(
                f"batch_size {batch_size} exceeds the {len(self)} available "
                f"windows (need more tokens in this split)"
            )
        start = (batch_index * batch_size) % (len(self) - batch_size + 1)
        return np.arange(start, start + batch_size)

    def sequential_batch(self, batch_index: int, batch_size: int) -> dict:
        """Unshuffled-loader equivalent (train.py:193-200)."""
        return self.batch(self.sequential_offsets(batch_index, batch_size))

    def batches(self, offsets: np.ndarray) -> dict:
        """Gather a stacked (n_batches, B, T) batch from (n_batches, B)
        offsets — the microbatch axis consumed by the train step's
        lax.scan."""
        n_batches, batch_size = offsets.shape
        flat = self.batch(offsets.reshape(-1))
        return {
            k: v.reshape(n_batches, batch_size, self.block_size)
            for k, v in flat.items()
        }

    def host_batches(self, offsets: np.ndarray) -> dict:
        """Numpy twin of :meth:`batches`: gather (n_batches, B_local)
        offsets into host arrays — the per-process local shard that
        ``parallel.multihost.global_batch`` assembles into one global
        jax.Array (the DistributedSampler capability, train.py:8-10)."""
        offsets = np.asarray(offsets)
        pos = offsets[..., None] + np.arange(self.block_size + 1)
        grab = self._host_tokens[pos]  # (n, B_local, T+1)
        return {"x": grab[..., :-1], "y": grab[..., 1:]}

    def random_batches(
        self, rng: np.random.Generator, batch_size: int, n_batches: int
    ) -> dict:
        """With-replacement sampling (the fast default deviation; see
        module docstring)."""
        offsets = rng.integers(0, len(self), size=(n_batches, batch_size), dtype=np.int64)
        return self.batches(offsets)
