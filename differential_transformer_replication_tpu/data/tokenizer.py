"""Byte-level BPE tokenizer training and corpus encoding.

Replicates ``create_and_train_tokenizer`` (train.py:27-55) and the
tokenize loop (train.py:165-172): a from-scratch ByteLevelBPE with
vocab_size=12000, min_frequency=2, special tokens ``<|endoftext|>`` and
``<|pad|>``; every document is encoded and followed by one
``<|endoftext|>`` id. This layer stays host-side Python by design
(SURVEY.md section 7.4) — the `tokenizers` library is Rust-backed and
already fast.

Fixed vs the reference: no module-global config access (train.py:36), no
temp-file round trip (train.py:35-37) — we train from the in-memory
iterator.
"""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

EOT = "<|endoftext|>"
PAD = "<|pad|>"


def train_bpe_tokenizer(
    texts: Sequence[str],
    vocab_size: int = 12000,
    min_frequency: int = 2,
    save_dir: str | None = "tokenizer",
):
    """Train ByteLevelBPE on the given texts (train.py:41-46) and
    optionally persist vocab+merges to ``save_dir`` (train.py:49-50)."""
    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        iter(texts),
        vocab_size=vocab_size,
        min_frequency=min_frequency,
        special_tokens=[EOT, PAD],
    )
    if save_dir:
        os.makedirs(save_dir, exist_ok=True)
        tok.save_model(save_dir)
    return tok


def load_tokenizer(save_dir: str):
    from tokenizers import ByteLevelBPETokenizer

    return ByteLevelBPETokenizer(
        os.path.join(save_dir, "vocab.json"), os.path.join(save_dir, "merges.txt")
    )


def vocab_strings(tok, vocab_size: int) -> List[str]:
    """The id -> decoded-text table the constraint FSM compiler walks
    (serving/constrain.py:build_token_fsm): entry i is exactly the
    text token i contributes to decoded output (byte-level markers
    resolved through the tokenizer's own decoder). Empty string — the
    compiler's "never allowed" marker — for ids outside the
    tokenizer's range (a padded model vocab) and for special tokens:
    an FSM must never advance through EOT/PAD, and a constrained
    request's EOS is compiled in separately on accepting states."""
    n = tok.get_vocab_size()
    specials = {tok.token_to_id(EOT), tok.token_to_id(PAD)}
    return [
        "" if (i >= n or i in specials) else tok.decode([i])
        for i in range(vocab_size)
    ]


def encode_corpus(tokenizer, texts: Sequence[str]) -> np.ndarray:
    """Encode all texts, appending one EOT id after each document
    (train.py:167-170). Returns a flat int32 token array."""
    eot_id = tokenizer.token_to_id(EOT)
    parts: List[np.ndarray] = []
    # encode_batch is the Rust-parallel path; the reference's per-text
    # Python loop (train.py:167) was a host bottleneck.
    for enc in tokenizer.encode_batch(list(texts)):
        parts.append(np.asarray(enc.ids + [eot_id], dtype=np.int32))
    return np.concatenate(parts) if parts else np.zeros((0,), np.int32)


def tokenizer_fingerprint(tok) -> str:
    """Content hash of the tokenizer's vocab (16 hex chars). Recorded in
    checkpoint meta at save time so downstream tools can verify they were
    handed the SAME tokenizer the model was trained with — equal vocab
    SIZE is not enough (every run targets 12000, so a shared tokenizer
    dir clobbered by a different corpus's run passes a size check with
    entirely different token ids)."""
    import hashlib
    import json as _json

    blob = _json.dumps(
        sorted(tok.get_vocab().items()), ensure_ascii=False
    ).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def check_tokenizer_matches(
    tok, model_vocab_size: int, expected_fingerprint: str | None = None,
    context: str = "",
) -> None:
    """Fail loud when a tokenizer cannot belong to the checkpointed
    model: vocab-size mismatch always; content-fingerprint mismatch when
    the checkpoint meta recorded one (older checkpoints did not). Both
    failure modes otherwise produce silently-valid token ids and garbage
    measurements (the per-run truth lives in
    ``<tokenizer_dir>/cache-<key>/``, which pairs vocab+tokens and
    cannot be cross-contaminated)."""
    where = f" for {context}" if context else ""
    if tok.get_vocab_size() != model_vocab_size:
        raise SystemExit(
            f"tokenizer vocab {tok.get_vocab_size()} != model vocab "
            f"{model_vocab_size}{where} — pass the tokenizer the "
            "checkpoint was trained with (usually "
            "<tokenizer_dir>/cache-<key>/ from its training run)"
        )
    if expected_fingerprint:
        fp = tokenizer_fingerprint(tok)
        if fp != expected_fingerprint:
            raise SystemExit(
                f"tokenizer content fingerprint {fp} != the checkpoint's "
                f"recorded {expected_fingerprint}{where}: same vocab "
                "size, different tokenizer (a shared tokenizer dir was "
                "likely overwritten by another run) — use the "
                "<tokenizer_dir>/cache-<key>/ copy from this "
                "checkpoint's training run"
            )
