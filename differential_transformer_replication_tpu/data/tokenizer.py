"""Byte-level BPE tokenizer training and corpus encoding.

Replicates ``create_and_train_tokenizer`` (train.py:27-55) and the
tokenize loop (train.py:165-172): a from-scratch ByteLevelBPE with
vocab_size=12000, min_frequency=2, special tokens ``<|endoftext|>`` and
``<|pad|>``; every document is encoded and followed by one
``<|endoftext|>`` id. This layer stays host-side Python by design
(SURVEY.md section 7.4) — the `tokenizers` library is Rust-backed and
already fast.

Fixed vs the reference: no module-global config access (train.py:36), no
temp-file round trip (train.py:35-37) — we train from the in-memory
iterator.
"""

from __future__ import annotations

import os
from typing import List, Sequence

import numpy as np

EOT = "<|endoftext|>"
PAD = "<|pad|>"


def train_bpe_tokenizer(
    texts: Sequence[str],
    vocab_size: int = 12000,
    min_frequency: int = 2,
    save_dir: str | None = "tokenizer",
):
    """Train ByteLevelBPE on the given texts (train.py:41-46) and
    optionally persist vocab+merges to ``save_dir`` (train.py:49-50)."""
    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        iter(texts),
        vocab_size=vocab_size,
        min_frequency=min_frequency,
        special_tokens=[EOT, PAD],
    )
    if save_dir:
        os.makedirs(save_dir, exist_ok=True)
        tok.save_model(save_dir)
    return tok


def load_tokenizer(save_dir: str):
    from tokenizers import ByteLevelBPETokenizer

    return ByteLevelBPETokenizer(
        os.path.join(save_dir, "vocab.json"), os.path.join(save_dir, "merges.txt")
    )


def encode_corpus(tokenizer, texts: Sequence[str]) -> np.ndarray:
    """Encode all texts, appending one EOT id after each document
    (train.py:167-170). Returns a flat int32 token array."""
    eot_id = tokenizer.token_to_id(EOT)
    parts: List[np.ndarray] = []
    # encode_batch is the Rust-parallel path; the reference's per-text
    # Python loop (train.py:167) was a host bottleneck.
    for enc in tokenizer.encode_batch(list(texts)):
        parts.append(np.asarray(enc.ids + [eot_id], dtype=np.int32))
    return np.concatenate(parts) if parts else np.zeros((0,), np.int32)
