from differential_transformer_replication_tpu.data.corpus import load_corpus
from differential_transformer_replication_tpu.data.tokenizer import (
    encode_corpus,
    load_tokenizer,
    train_bpe_tokenizer,
)
from differential_transformer_replication_tpu.data.sampler import TokenWindows, split_tokens

__all__ = [
    "load_corpus",
    "train_bpe_tokenizer",
    "load_tokenizer",
    "encode_corpus",
    "TokenWindows",
    "split_tokens",
]
