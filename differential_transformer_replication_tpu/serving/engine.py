"""Continuous-batching inference engine.

The single-request generators (models/generate.py, models/decode.py:
``generate_cached``) answer one prompt at a time; a serving workload has
many concurrent users with different prompt lengths, arrival times and
sampling params. This engine closes that gap with the two standard
techniques:

- **Slot-pool KV cache** (vLLM-style, minus paging): one fixed
  ``init_cache(cfg, num_slots)`` pool holds every in-flight sequence's
  K/V rings. A request owns one slot row from admission to retirement;
  rows are reused WITHOUT clearing because the ring mask derives
  visibility purely from position arithmetic (models/decode.py:
  ``_attn_chunk``) — a fresh prefill at pos=0 makes every stale key
  invisible by construction.
- **Iteration-level (Orca-style) scheduling**: each :meth:`step` admits
  queued requests into free slots, advances prefill by a bounded token
  budget (serving/scheduler.py), then decodes ALL active slots as one
  batched length-1 ``forward_chunk``. Sequences retire on EOS or
  max-tokens without stalling the rest of the batch; the freed slot is
  refilled on the next iteration.

Everything device-side is shape-static, so continuous batching costs no
recompilation as requests come and go:

- the decode step is one jitted call over the FULL pool — per-slot
  positions/tokens/active-mask are runtime arrays (inactive rows compute
  garbage that a masked cache-merge discards);
- prefill chunks come from a power-of-two ladder, so at most
  log2(prefill_chunk)+1 prefill shapes ever compile;
- sampling is one jitted batched kernel with per-row temperature/top-k
  ARRAYS (models/generate.py:``sample_token`` bakes them into the trace
  as statics; rows here must differ without recompiling). The greedy and
  default paths are bit-identical to ``sample_token`` — pinned by
  tests/test_serving.py.

Mixed per-slot positions ride a ``jax.vmap`` over ``forward_chunk``
(each row carries its own ``pos`` scalar, exactly the traced-position
path the chunked decoder already supports); ``forward_chunk``'s
concrete-position validity guards are enforced host-side at submit
instead. Per-request determinism: the key for the t-th generated token
is ``fold_in(PRNGKey(seed), t)``, a pure function of the request — not
of slot assignment, batch composition, or admission order.

Family limits (models/decode.py module docstring): control/ndiff roll
the ring past block_size up to ``ServingConfig.max_seq_len``; the diff
family's learned absolute position table cannot roll, so its requests
are capped at ``prompt + max_new_tokens <= block_size``.
"""

from __future__ import annotations

import time
from functools import lru_cache
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from differential_transformer_replication_tpu.config import (
    ModelConfig,
    ServingConfig,
)
from differential_transformer_replication_tpu.models.decode import (
    forward_chunk,
    init_cache,
)
from differential_transformer_replication_tpu.serving.request import (
    Request,
    RequestOutput,
    SamplingParams,
)
from differential_transformer_replication_tpu.serving.scheduler import (
    ACTIVE,
    FREE,
    Scheduler,
    Slot,
)
from differential_transformer_replication_tpu.utils import faults


class EngineCrashError(RuntimeError):
    """The engine failed mid-flight (device error, corrupt slot pool,
    non-finite logits). Typed and RETRIABLE: the supervised runner
    (serving/server.py) fails in-flight requests with this error,
    rebuilds the slot pool from params, and serves on — a client that
    retries (HTTP 503 + Retry-After) lands on the restarted engine."""

    retriable = True


@lru_cache(maxsize=None)
def _build_step_fns(cfg: ModelConfig, rope_len: int):
    """Jitted (prefill, decode, sample) closures for (cfg, rope_len).

    Cached at module level so engines with the same model/config share
    compile caches (and tests can count compiles across engine
    rebuilds); every argument below is a runtime array, so each closure
    compiles once per distinct input SHAPE only.
    """
    row_axes = [{"k": 1, "v": 0}] * cfg.n_layer  # pool layout per layer

    def _one_row(params, token, pos, cache_row):
        # cache_row: per-layer {"k": (S, M, H, d), "v": (M, H, dv)} — one
        # pool row; re-add the batch axis forward_chunk expects.
        cache_b = [
            {"k": c["k"][:, None], "v": c["v"][None]} for c in cache_row
        ]
        logits, new_cache = forward_chunk(
            params, token[None, None], pos, cache_b, cfg, rope_len=rope_len
        )
        new_row = [{"k": c["k"][:, 0], "v": c["v"][0]} for c in new_cache]
        return logits[0, -1].astype(jnp.float32), new_row

    def _decode(params, tokens, pos, active, cache):
        """One batched length-1 step over the WHOLE slot pool.

        tokens/pos/active: (B,) runtime arrays. Inactive rows run the
        same math on garbage inputs (static shapes are the point); the
        masked merge below discards their cache writes so a mid-prefill
        or free slot is never corrupted by the fused step.
        """
        logits, new_cache = jax.vmap(
            _one_row, in_axes=(None, 0, 0, row_axes), out_axes=(0, row_axes)
        )(params, tokens, pos, cache)
        merged = [
            {
                "k": jnp.where(
                    active[None, :, None, None, None], nc["k"], oc["k"]
                ),
                "v": jnp.where(active[:, None, None, None], nc["v"], oc["v"]),
            }
            for nc, oc in zip(new_cache, cache)
        ]
        return logits, merged

    def _prefill(params, cache, slot, tokens, pos):
        """One prompt chunk for one slot, in place in the pool.

        tokens: (1, L) with L from the power-of-two ladder; slot/pos are
        runtime scalars (dynamic gather/scatter on the pool's batch
        axis), so only L distinguishes compiles.
        """
        row = [
            {"k": c["k"][:, slot][:, None], "v": c["v"][slot][None]}
            for c in cache
        ]
        logits, new_row = forward_chunk(
            params, tokens, pos, row, cfg, rope_len=rope_len
        )
        new_cache = [
            {
                "k": c["k"].at[:, slot].set(nr["k"][:, 0]),
                "v": c["v"].at[slot].set(nr["v"][0]),
            }
            for c, nr in zip(cache, new_row)
        ]
        return logits[0, -1].astype(jnp.float32), new_cache

    def _sample(bases, counts, logits, temperature, top_k):
        """Batched per-request sampling over (B, V) fp32 logits.

        bases (B, 2) uint32 + counts (B,): the t-th token's key is
        fold_in(base, t). temperature/top_k are PER-ROW arrays;
        semantics match sample_token row-for-row (<=0 temp = greedy,
        top_k <= 0 = off, mask-below-kth-logit otherwise).

        Also returns a per-row finiteness flag over the RAW logits
        (before the intentional top-k -inf masking): a corrupt KV slot
        or numerically diverged model yields NaN logits, and serving a
        garbage argmax over them would be a silent wrong answer — the
        engine turns a non-finite ACTIVE row into a typed
        :class:`EngineCrashError` instead (inactive rows compute
        garbage by design and are ignored host-side). The reduction
        fuses into the sampling kernel; the extra transfer is (B,) bools.
        """
        keys = jax.vmap(jax.random.fold_in)(bases, counts)
        V = logits.shape[-1]
        kth = jnp.clip(top_k - 1, 0, V - 1)
        sorted_desc = -jnp.sort(-logits, axis=-1)
        thresh = jnp.take_along_axis(sorted_desc, kth[:, None], axis=-1)
        masked = jnp.where(
            (top_k > 0)[:, None] & (logits < thresh), -jnp.inf, logits
        )
        greedy = jnp.argmax(masked, axis=-1)
        safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
        drawn = jax.vmap(lambda k, lg: jax.random.categorical(k, lg))(
            keys, masked / safe_t
        )
        tokens = jnp.where(temperature <= 0, greedy, drawn).astype(jnp.int32)
        return tokens, jnp.isfinite(logits).all(axis=-1)

    # Donate the cache pool so XLA updates it in place instead of
    # allocating + copying a second full pool per chunk/step (the engine
    # always rebinds self.cache to the result, so the old buffers are
    # dead). CPU has no donation support and would warn on every call.
    donate = jax.default_backend() != "cpu"
    return (
        jax.jit(_prefill, donate_argnums=(1,) if donate else ()),
        jax.jit(_decode, donate_argnums=(4,) if donate else ()),
        jax.jit(_sample),
    )


class ServingEngine:
    """Continuous-batching engine over one model's params.

    Drive it either synchronously — ``submit()`` then ``run()`` /
    ``generate()`` — or one :meth:`step` at a time (what the background
    thread in serving/server.py does). Not thread-safe by itself; wrap
    it in :class:`serving.server.EngineRunner` for concurrent callers.
    """

    def __init__(self, params: dict, cfg: ModelConfig,
                 serving: Optional[ServingConfig] = None):
        self.params = params
        self.cfg = cfg
        self.serving = serving or ServingConfig()
        self.max_total = self.serving.resolved_max_seq_len(cfg)
        self._prefill_fn, self._decode_fn, self._sample_fn = _build_step_fns(
            cfg, self.max_total
        )
        self.cache = init_cache(cfg, self.serving.num_slots)
        self.scheduler = Scheduler(self.serving)
        self._next_id = 0
        self._base_keys: dict = {}  # request_id -> np (2,) uint32 PRNG base
        # outputs produced by a step() that later RAISED: the finished/
        # shed requests were already retired from the scheduler, so they
        # would be unreachable after the crash (neither slot-holding nor
        # queued) — the buffer keeps them deliverable (take_finished)
        self._finished_prior: List[RequestOutput] = []
        self.stats = {
            "iterations": 0,
            "prefill_tokens": 0,
            "decode_tokens": 0,
            "completed": 0,
            "cancelled": 0,
            "rejected": 0,
            "deadline_expired": 0,
            "engine_restarts": 0,
        }

    # -- submission ---------------------------------------------------

    def submit(self, prompt: Sequence[int],
               params: Optional[SamplingParams] = None,
               deadline: Optional[float] = None, **kw) -> int:
        """Queue one request; returns its request_id. ``kw`` are
        SamplingParams fields (max_new_tokens, temperature, top_k, seed,
        eos_token_id). ``deadline`` is an ABSOLUTE ``time.perf_counter``
        timestamp after which the engine stops working on the request
        (shed at admission / retired mid-decode, ``finish_reason ==
        "deadline"``); None applies ``ServingConfig.default_deadline_s``
        when set. Raises ValueError when the request cannot fit the
        engine's static shapes (see module docstring on family limits).
        """
        rid = self._next_id
        self._next_id += 1
        req = Request.make(rid, prompt, params, **kw)
        M = self.cfg.block_size
        p = np.asarray(req.prompt, np.int32)
        if self.cfg.model == "diff":
            if p.shape[0] + req.params.max_new_tokens > M:
                raise ValueError(
                    f"prompt ({p.shape[0]}) + max_new_tokens "
                    f"({req.params.max_new_tokens}) exceeds block_size ({M}) "
                    "and the diff family's learned absolute position table "
                    "cannot roll with a KV cache (models/decode.py)"
                )
        else:
            if p.shape[0] > M:
                p = p[-M:]  # the reference's own crop (control.py:165)
            if p.shape[0] + req.params.max_new_tokens > self.max_total:
                raise ValueError(
                    f"cropped prompt ({p.shape[0]}) + max_new_tokens "
                    f"({req.params.max_new_tokens}) exceeds the engine's "
                    f"max_seq_len ({self.max_total}); build the engine with "
                    "a larger ServingConfig.max_seq_len"
                )
        now = time.perf_counter()
        if deadline is None and self.serving.default_deadline_s > 0:
            deadline = now + self.serving.default_deadline_s
        # admission bound first (scheduler.submit raises QueueFullError
        # when the wait queue is at ServingConfig.max_queue_len) — a
        # rejected request must leave no key-chain entry behind
        try:
            self.scheduler.submit(req, p, now, deadline or 0.0)
        except Exception:
            self.stats["rejected"] += 1
            raise
        self._base_keys[rid] = np.asarray(
            jax.random.PRNGKey(req.params.seed), np.uint32
        )
        return rid

    def cancel(self, request_id: int) -> bool:
        """Abandon an in-flight request: dropped from the wait queue, or
        its slot retired so the KV rows return to the pool. Without this
        a caller that times out leaves the engine decoding to completion
        for nobody — the slot leak serving/server.py's timeout path used
        to have. Returns False when the request is unknown or already
        finished (its output was, or is about to be, delivered)."""
        if request_id not in self._base_keys:
            return False
        self.scheduler.cancel(request_id)
        del self._base_keys[request_id]
        self.stats["cancelled"] += 1
        return True

    # -- one engine iteration -----------------------------------------

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def queue_len(self) -> int:
        """Requests waiting for a slot (admission-queue depth)."""
        return self.scheduler.queue_len()

    def step(self) -> List[RequestOutput]:
        """Deadline shed -> admit -> prefill (budgeted) -> batched
        decode. Returns the requests that finished THIS iteration
        (including ones retired with ``finish_reason == "deadline"``)."""
        if not self.scheduler.has_work():
            out, self._finished_prior = self._finished_prior, []
            return out
        faults.serve_fire(self.stats["iterations"])
        # build into the survives-an-exception buffer: a request that
        # finishes (or is deadline-shed) early in this step and is
        # already retired must still reach its caller when a LATER part
        # of the same step crashes (see take_finished)
        finished = self._finished_prior

        # deadline enforcement, both placements, BEFORE device work:
        # expired queue entries never get a slot, expired slots return
        # their KV rows to the pool instead of decoding for nobody
        now = time.perf_counter()
        for req, prompt, t_submit, _dl in self.scheduler.shed_expired(now):
            finished.append(self._expire_queued(req, prompt, t_submit, now))
        for slot in self.scheduler.expired_slots(now):
            finished.append(self._finish(slot, "deadline", now=now))

        for slot, start, size in self.scheduler.plan():
            tokens = jnp.asarray(slot.prompt[start:start + size][None])
            logits, self.cache = self._prefill_fn(
                self.params, self.cache, np.int32(slot.index), tokens,
                np.int32(start),
            )
            slot.filled = start + size
            self.stats["prefill_tokens"] += size
            if slot.filled == slot.prompt_len:
                # prompt complete: the chunk's last-position logits give
                # the first generated token (generate_cached's contract)
                tok, ok = self._sample_rows([slot], logits[None])
                if not ok[0]:
                    raise EngineCrashError(
                        f"non-finite logits prefilling slot {slot.index} "
                        f"(request {slot.request.request_id}): corrupt "
                        "slot pool or numerically diverged params"
                    )
                self._emit(slot, int(tok[0]), time.perf_counter(), finished)

        if faults.serve_corrupt_at(self.stats["iterations"]):
            self._corrupt_one_slot()

        active = self.scheduler.active_slots()
        if active:
            B = self.serving.num_slots
            tokens = np.zeros((B,), np.int32)
            pos = np.zeros((B,), np.int32)
            mask = np.zeros((B,), bool)
            for s in active:
                tokens[s.index] = s.generated[-1]
                pos[s.index] = s.prompt_len + len(s.generated) - 1
                mask[s.index] = True
            logits, self.cache = self._decode_fn(
                self.params, jnp.asarray(tokens), jnp.asarray(pos),
                jnp.asarray(mask), self.cache,
            )
            sampled, ok = self._sample_all_slots(logits)
            bad = [s for s in active if not ok[s.index]]
            if bad:
                raise EngineCrashError(
                    f"non-finite logits decoding slot(s) "
                    f"{[s.index for s in bad]} (request(s) "
                    f"{[s.request.request_id for s in bad]}): corrupt "
                    "slot pool or numerically diverged params"
                )
            now = time.perf_counter()
            self.stats["decode_tokens"] += len(active)
            for s in active:
                self._emit(s, int(sampled[s.index]), now, finished)

        self.stats["iterations"] += 1
        self._finished_prior = []
        return finished

    def take_finished(self) -> List[RequestOutput]:
        """Outputs accumulated by a :meth:`step` that raised partway
        through. Those requests were already retired (slot freed / shed
        from the queue), so after a crash they are invisible to both
        :meth:`reset_after_crash`'s lost-list and the preserved queue —
        the supervisor (serving/server.py) must drain this buffer and
        deliver them, or their callers would hang forever."""
        out, self._finished_prior = self._finished_prior, []
        return out

    def run(self) -> List[RequestOutput]:
        """Drain the queue; returns every output, in completion order."""
        outs: List[RequestOutput] = []
        while self.scheduler.has_work():
            outs.extend(self.step())
        return outs

    def generate(self, prompts: Sequence[Sequence[int]],
                 params: Optional[Sequence[SamplingParams]] = None,
                 **kw) -> List[RequestOutput]:
        """Submit-all + drain convenience; outputs in submission order.
        ``params`` gives per-request SamplingParams; otherwise ``kw``
        build one shared SamplingParams."""
        shared = SamplingParams(**kw) if params is None else None
        ids = []
        try:
            for i, p in enumerate(prompts):
                ids.append(self.submit(p, params=shared if shared else params[i]))
        except Exception:
            # mid-batch rejection (max_queue_len): the prompts already
            # queued would otherwise sit in the scheduler and burn a
            # later run()'s decode iterations for nobody
            for rid in ids:
                self.cancel(rid)
            raise
        by_id = {o.request_id: o for o in self.run()}
        return [by_id[i] for i in ids]

    def compile_stats(self) -> dict:
        """Compile-cache sizes of the engine's jitted closures. Pinned by
        tests/test_serving.py: decode must stay at 1 entry no matter how
        requests come and go. NOTE the closures are shared across engines
        with identical (cfg, max_seq_len) — counts are per-config, not
        per-instance."""
        return {
            "prefill": self._prefill_fn._cache_size(),
            "decode": self._decode_fn._cache_size(),
            "sample": self._sample_fn._cache_size(),
        }

    # -- internals ----------------------------------------------------

    def _sample_rows(self, slots: List[Slot], logits):
        """Sample one token for each given slot from (n, V) logits;
        returns (tokens, finite-ok) per row."""
        bases = jnp.asarray(
            np.stack([
                self._base_keys[s.request.request_id] for s in slots
            ])
        )
        counts = jnp.asarray(
            [len(s.generated) for s in slots], jnp.int32
        )
        temps = jnp.asarray(
            [s.request.params.temperature for s in slots], jnp.float32
        )
        topks = jnp.asarray(
            [(s.request.params.top_k or 0) for s in slots], jnp.int32
        )
        toks, ok = self._sample_fn(bases, counts, logits, temps, topks)
        return np.asarray(toks), np.asarray(ok)

    def _sample_all_slots(self, logits):
        """Full-pool variant with inert defaults on non-active rows, so
        the decode-path sampler always sees the same (B, V) shape.
        Returns (tokens, finite-ok); only ACTIVE rows' flags mean
        anything (inactive rows compute garbage by design)."""
        B = self.serving.num_slots
        bases = np.zeros((B, 2), np.uint32)
        counts = np.zeros((B,), np.int32)
        temps = np.ones((B,), np.float32)
        topks = np.zeros((B,), np.int32)
        for s in self.scheduler.active_slots():
            p = s.request.params
            bases[s.index] = self._base_keys[s.request.request_id]
            counts[s.index] = len(s.generated)
            temps[s.index] = p.temperature
            topks[s.index] = p.top_k or 0
        toks, ok = self._sample_fn(
            jnp.asarray(bases), jnp.asarray(counts), logits,
            jnp.asarray(temps), jnp.asarray(topks),
        )
        return np.asarray(toks), np.asarray(ok)

    def _emit(self, slot: Slot, token: int, now: float,
              finished: List[RequestOutput]) -> None:
        slot.generated.append(token)
        slot.token_times.append(now)
        if len(slot.generated) == 1:
            slot.first_token_time = now
            slot.state = ACTIVE
        p = slot.request.params
        eos = (
            p.eos_token_id
            if p.eos_token_id is not None
            else self.serving.eos_token_id
        )
        hit_eos = eos is not None and token == eos
        if hit_eos or len(slot.generated) >= p.max_new_tokens:
            finished.append(
                self._finish(slot, "eos" if hit_eos else "length")
            )

    def _finish(self, slot: Slot, reason: str,
                now: Optional[float] = None) -> RequestOutput:
        out = RequestOutput(
            request_id=slot.request.request_id,
            prompt=[int(t) for t in slot.prompt],
            tokens=list(slot.generated),
            finish_reason=reason,
            submit_time=slot.submit_time,
            first_token_time=slot.first_token_time,
            # a slot retired at its deadline may not have produced a
            # single token yet (still prefilling)
            finish_time=(
                slot.token_times[-1] if slot.token_times
                else (now if now is not None else time.perf_counter())
            ),
            token_times=list(slot.token_times),
        )
        del self._base_keys[slot.request.request_id]
        if reason == "deadline":
            self.stats["deadline_expired"] += 1
        else:
            self.stats["completed"] += 1
        self.scheduler.retire(slot)
        return out

    def _expire_queued(self, request, prompt, submit_time: float,
                       now: float) -> RequestOutput:
        """A request whose deadline passed while it waited for a slot:
        it never touches the device; the caller gets a typed error."""
        self._base_keys.pop(request.request_id, None)
        self.stats["deadline_expired"] += 1
        return RequestOutput(
            request_id=request.request_id,
            prompt=[int(t) for t in prompt],
            tokens=[],
            finish_reason="deadline",
            submit_time=submit_time,
            first_token_time=0.0,
            finish_time=now,
            token_times=[],
        )

    def _corrupt_one_slot(self) -> None:
        """Fault-injection helper (``serve_corrupt@N``): NaN-poison one
        occupied slot's KV rows. Prefers an ACTIVE slot — the ring mask
        derives visibility from position arithmetic, so poison in
        not-yet-written positions would stay invisible; an active
        slot's already-written keys are visible and the next decode
        step's logits go NaN, tripping the finite-logits guard."""
        target = next(
            (s for s in self.scheduler.slots if s.state == ACTIVE), None
        ) or next(
            (s for s in self.scheduler.slots
             if s.state != FREE and s.filled > 0), None
        )
        if target is None:
            return
        i = target.index
        self.cache = [
            {"k": c["k"].at[:, i].set(jnp.nan),
             "v": c["v"].at[i].set(jnp.nan)}
            for c in self.cache
        ]

    # -- crash recovery (serving/server.py supervision) ----------------

    def reset_after_crash(self) -> List[int]:
        """Rebuild device-side state after a failed :meth:`step`.

        A crashed step leaves the engine untrusted: the jitted calls
        donate the cache pool, so a failure mid-call may have
        invalidated (or poisoned) it. Params are immutable jax arrays —
        never donated, never written — so the pool is rebuilt from
        scratch exactly as ``__init__`` built it, and the jitted
        closures are reused from the module-level cache (a restart adds
        ZERO recompiles; pinned by tests/test_serving_resilience.py).

        Requests that held slots (in-flight) lost device state and are
        FAILED — their request_ids are returned for the supervisor to
        error out with :class:`EngineCrashError`. Requests still in the
        wait queue never touched the device and are preserved verbatim
        (same request_id, prompt, deadline, PRNG base), so they complete
        normally after the restart. Stats survive;
        ``stats["engine_restarts"]`` counts the rebuilds.
        """
        lost: List[int] = []
        for slot in self.scheduler.slots:
            if slot.state != FREE and slot.request is not None:
                rid = slot.request.request_id
                lost.append(rid)
                self._base_keys.pop(rid, None)
        preserved = list(self.scheduler.queue)
        self.cache = init_cache(self.cfg, self.serving.num_slots)
        self.scheduler = Scheduler(self.serving)
        self.scheduler.queue.extend(preserved)
        self.stats["engine_restarts"] += 1
        return lost
